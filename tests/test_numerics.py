"""Numerics observability tier (FLAGS_check_numerics): the in-graph
tensor-health instrumentation pass (analysis/numerics.py +
ops/numerics_ops.py), the monitor-side gauges/locate machinery
(monitor/numerics.py), and the wiring into executor, watchdog, flight,
amp, and chaos.

Red gates: a chaos-injected NaN at a KNOWN op (mid-network, inside a
while sub-block, in a grad op) must be named — exactly that op — by the
locate replay.  Zero-cost-off is asserted byte-for-byte (fingerprint
identity, one flag read, no registry entries).  Summary gauges are
hand-checked against numpy grads fetched from an uninstrumented twin.
"""

import json
import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, monitor
from paddle_tpu.analysis import numerics as anum
from paddle_tpu.flags import FLAGS
from paddle_tpu.monitor import flight
from paddle_tpu.monitor import numerics as mnum
from paddle_tpu.monitor.watchdog import Watchdog
from paddle_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _fresh_numerics():
    """Flags / registry / flight / chaos / numerics state isolation."""
    from paddle_tpu import amp

    FLAGS.reset()
    monitor.default_registry().reset()
    flight.default_recorder().clear()
    chaos.reset()
    mnum.reset()
    amp.set_loss_scaler(None)
    yield
    FLAGS.reset()
    monitor.default_registry().reset()
    flight.default_recorder().clear()
    chaos.reset()
    mnum.reset()
    amp.set_loss_scaler(None)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------


def _mlp(act="relu", lr=0.01, dropout=0.0):
    """Tiny train net on the default programs; returns the loss var."""
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act=act,
                  param_attr=pt.ParamAttr(name="w1"),
                  bias_attr=pt.ParamAttr(name="b1"))
    if dropout:
        h = layers.dropout(h, dropout_prob=dropout,
                           dropout_implementation="upscale_in_train")
    pred = layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                     bias_attr=pt.ParamAttr(name="b2"))
    loss = layers.mean(layers.square(pred - y))
    pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    return loss


def _feed(bs=4, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(bs, 8).astype("float32"),
            "y": rng.randn(bs, 1).astype("float32")}


def _op_output(prog, op_type, which=0):
    """Name of the `which`-th output var of the first `op_type` op."""
    hits = [op for op in prog.global_block().ops if op.type == op_type]
    assert hits, f"no {op_type!r} op in program"
    return hits[0].output_arg_names()[which]


def _run_locate_replay(loss, target_var, feed=None):
    """Arm chaos poison on `target_var` + locate capture, run one step,
    and return the replay verdict."""
    FLAGS.monitor = True
    FLAGS.chaos = True
    FLAGS.chaos_nan_var = target_var
    FLAGS.check_numerics = "locate"
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(feed=feed or _feed(), fetch_list=[loss])
    assert mnum.last_capture() is not None
    verdict = mnum.locate_replay(step=1)
    assert verdict is not None
    return verdict


# ---------------------------------------------------------------------------
# zero-cost off mode
# ---------------------------------------------------------------------------


class TestOffMode:
    def test_off_is_zero_cost_and_one_flag_read(self, monkeypatch):
        loss = _mlp()
        prog = pt.default_main_program()
        fp0 = prog.fingerprint()

        reads = []
        orig = type(FLAGS).__getattr__

        def spy(self, name):
            if name == "check_numerics":
                reads.append(name)
            return orig(self, name)

        monkeypatch.setattr(type(FLAGS), "__getattr__", spy)
        assert anum.maybe_instrument(prog) is None
        monkeypatch.setattr(type(FLAGS), "__getattr__", orig)

        assert reads == ["check_numerics"]  # exactly ONE flag read
        assert prog.fingerprint() == fp0    # byte-identical graph
        assert not anum.is_instrumented(prog)

        # a run publishes nothing and fetches only what was asked
        FLAGS.monitor = True
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        outs = exe.run(feed=_feed(), fetch_list=[loss])
        assert len(outs) == 1
        assert mnum.last_summary() is None
        assert mnum.last_capture() is None
        numerics_metrics = [n for n in monitor.default_registry().names()
                            if n.startswith("numerics")]
        assert numerics_metrics == []

    def test_locate_mode_defers_graph_rewrite(self):
        _mlp()
        prog = pt.default_main_program()
        fp0 = prog.fingerprint()
        rep = anum.maybe_instrument(prog, level="locate")
        assert rep == {"level": "locate", "rows": 0, "deferred": True}
        assert prog.fingerprint() == fp0  # steady-state graph unchanged

    def test_bad_level_and_double_instrument_raise(self):
        _mlp()
        prog = pt.default_main_program()
        with pytest.raises(ValueError, match="check_numerics level"):
            anum.instrument_program(prog, "verbose")
        anum.instrument_program(prog, "summary")
        with pytest.raises(ValueError, match="already"):
            anum.instrument_program(prog, "summary")


# ---------------------------------------------------------------------------
# the fused stat op (vs numpy)
# ---------------------------------------------------------------------------


class TestStatRows:
    def test_stat_row_matches_numpy(self):
        """Instrument a one-op program in locate mode and hand-check the
        fetched row (nonfinite count, finite-masked abs stats) vs numpy."""
        x = layers.data(name="x", shape=[6], dtype="float32")
        out = layers.scale(x, scale=2.0)
        prog = pt.default_main_program()
        anum.instrument_program(prog, "locate")

        xv = np.array([[1.0, -3.0, np.nan, np.inf, 0.5, -np.inf]],
                      dtype="float32")
        exe = pt.Executor(pt.CPUPlace())
        FLAGS.monitor = True
        outs = exe.run(feed={"x": xv}, fetch_list=[out])
        assert len(outs) == 1  # stats stripped from user results

        snap = mnum._last_stats
        assert snap is not None and snap["level"] == "locate"
        by_var = {r["var"]: r["stat"] for r in snap["rows"]}
        st = by_var[out.name]
        ref = 2.0 * xv.astype(np.float64)
        finite = np.isfinite(ref)
        ax = np.abs(np.where(finite, ref, 0.0))
        assert st["nonfinite"] == float((~finite).sum())
        np.testing.assert_allclose(st["abs_max"], ax.max(), rtol=1e-6)
        np.testing.assert_allclose(st["abs_mean"], ax.mean(), rtol=1e-6)
        np.testing.assert_allclose(st["l2"],
                                   math.sqrt((ax * ax).sum()), rtol=1e-6)

    def test_single_extra_fetch_per_step(self):
        """The packing contract: locate mode adds exactly the packed
        stats tensor(s) to the fetch, not one fetch per op."""
        x = layers.data(name="x", shape=[4], dtype="float32")
        h = layers.scale(x, scale=2.0)
        out = layers.scale(h, scale=0.5)
        prog = pt.default_main_program()
        rep = anum.instrument_program(prog, "locate")
        assert rep["rows"] >= 2
        assert prog._numerics_stats_vars == [anum.STATS_VAR]
        exe = pt.Executor(pt.CPUPlace())
        user_fetch = [out.name]
        n, full = exe._numerics_fetch(prog, user_fetch)
        assert n == 1 and full == [out.name, anum.STATS_VAR]


# ---------------------------------------------------------------------------
# summary mode: gauges hand-checked vs numpy
# ---------------------------------------------------------------------------


class TestSummaryGauges:
    def test_gauges_match_numpy_grads(self):
        lr = 0.05
        loss = _mlp(act="tanh", lr=lr)
        prog = pt.default_main_program()
        twin = prog.clone()  # uninstrumented twin for the numpy reference
        anum.instrument_program(prog, "summary")

        exe = pt.Executor(pt.CPUPlace())
        scope_a, scope_b = pt.Scope(), pt.Scope()
        exe.run(pt.default_startup_program(), scope=scope_a)
        exe.run(pt.default_startup_program(), scope=scope_b)
        init = {n: np.asarray(scope_a.find_var(n)).copy()
                for n in ("w1", "b1", "w2", "b2")}
        for n, v in init.items():
            scope_b.set_var(n, v)

        feed = _feed(seed=3)
        grads = exe.run(twin, feed=feed, scope=scope_a,
                        fetch_list=[f"{n}@GRAD"
                                    for n in ("w1", "b1", "w2", "b2")])
        g = {n: np.asarray(v, dtype=np.float64)
             for n, v in zip(("w1", "b1", "w2", "b2"), grads)}
        post = {n: init[n].astype(np.float64) - lr * g[n] for n in g}

        FLAGS.monitor = True
        exe.run(prog, feed=feed, scope=scope_b, fetch_list=[loss])
        summ = mnum.last_summary()
        assert summ is not None and summ["grad_nonfinite"] == 0

        expect_gn = math.sqrt(sum((gv ** 2).sum() for gv in g.values()))
        np.testing.assert_allclose(summ["grad_norm"], expect_gn, rtol=1e-4)
        reg = monitor.default_registry()
        np.testing.assert_allclose(reg.get("numerics.grad_norm").value,
                                   expect_gn, rtol=1e-4)
        for grp in ("w1", "b1", "w2", "b2"):
            gg = summ["groups"][grp]
            wn = math.sqrt((post[grp] ** 2).sum())
            un = lr * math.sqrt((g[grp] ** 2).sum())
            np.testing.assert_allclose(gg["weight_norm"], wn, rtol=1e-4)
            np.testing.assert_allclose(gg["update_norm"], un, rtol=1e-4)
            np.testing.assert_allclose(gg["update_ratio"], un / wn,
                                       rtol=1e-4)
            np.testing.assert_allclose(
                reg.get(f"numerics.update_ratio.{grp}").value, un / wn,
                rtol=1e-4)
        # flight carries the per-step summary event
        evs = flight.default_recorder().events(kind="numerics.summary")
        assert evs and evs[-1]["grad_nonfinite"] == 0

    def test_instrumented_program_verifies_clean(self):
        from paddle_tpu.analysis import verify_program

        loss = _mlp()
        prog = pt.default_main_program()
        anum.instrument_program(prog, "summary")
        findings = verify_program(
            prog, feed_names=["x", "y"],
            fetch_names=[loss.name] + list(prog._numerics_stats_vars),
            check_dead=True)
        assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# locate red gates: the injected op must be named, exactly
# ---------------------------------------------------------------------------


class TestLocateRedGates:
    def test_names_mid_network_op(self):
        loss = _mlp(act="relu")
        target = _op_output(pt.default_main_program(), "relu")
        v = _run_locate_replay(loss, target)
        assert v["var"] == target
        assert v["op_type"] == "relu"
        assert v["replayed"] is True
        assert v["stat"]["nonfinite"] > 0
        assert v["first_bad_op"].startswith("relu@block0:")
        assert mnum.last_locate_result() == v

    def test_names_grad_op(self):
        loss = _mlp(act="tanh")
        prog = pt.default_main_program()
        target = _op_output(prog, "square_grad")
        v = _run_locate_replay(loss, target)
        assert v["var"] == target
        assert v["op_type"] == "square_grad"
        assert v["replayed"] is True

    def test_names_op_inside_while_block(self):
        i = layers.fill_constant([1], "float32", 0.0)
        total = layers.fill_constant([1], "float32", 0.0)
        limit = layers.fill_constant([1], "float32", 10.0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            new_total = layers.elementwise_add(total, i)
            layers.assign(new_total, output=total)
            new_i = layers.scale(i, scale=1.0, bias=1.0)
            layers.assign(new_i, output=i)
            layers.less_than(i, limit, cond=cond)

        FLAGS.monitor = True
        FLAGS.chaos = True
        FLAGS.chaos_nan_var = new_total.name
        FLAGS.check_numerics = "locate"
        exe = pt.Executor(pt.CPUPlace())
        (t,) = exe.run(fetch_list=[total])
        assert not np.isfinite(t).all()
        v = mnum.locate_replay(step=1)
        assert v is not None
        assert v["var"] == new_total.name
        assert v["op_type"] == "elementwise_add"
        assert v["in_loop"] is True
        assert v["block"] > 0  # named inside the sub-block, not the while

    def test_clean_replay_names_nothing(self):
        loss = _mlp()
        FLAGS.check_numerics = "locate"
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        exe.run(feed=_feed(), fetch_list=[loss])
        v = mnum.locate_replay(step=1)
        assert v is not None and v["first_bad_op"] is None
        assert v["rows_checked"] > 10

    def test_forced_run_id_replays_dropout_bitwise(self):
        """The determinism contract under the replay: forcing the failing
        step's run id reproduces the SAME dropout masks, so the replayed
        loss is bit-identical; an unforced re-run draws fresh masks."""
        # forward-only net (no optimizer): scope state is identical across
        # runs, so any loss difference is purely the dropout mask
        x = layers.data(name="x", shape=[8], dtype="float32")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=pt.ParamAttr(name="w1"),
                      bias_attr=pt.ParamAttr(name="b1"))
        h = layers.dropout(h, dropout_prob=0.5,
                           dropout_implementation="upscale_in_train")
        loss = layers.mean(h)
        FLAGS.check_numerics = "locate"
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        feed = _feed(bs=16)
        (l1,) = exe.run(feed=feed, fetch_list=[loss])
        ctx = mnum.last_capture()
        assert ctx is not None
        exe._forced_run_id = ctx["run_id"]
        (l2,) = exe.run(feed=feed, fetch_list=[loss])
        assert np.asarray(l1).tobytes() == np.asarray(l2).tobytes()
        (l3,) = exe.run(feed=feed, fetch_list=[loss])  # fresh masks
        assert np.asarray(l3).tobytes() != np.asarray(l1).tobytes()


# ---------------------------------------------------------------------------
# watchdog end-to-end: trip -> replay -> flight dump names the op
# ---------------------------------------------------------------------------


class TestWatchdogEndToEnd:
    def test_nan_trip_dump_names_injected_op(self, tmp_path):
        FLAGS.monitor = True
        FLAGS.flight_dir = str(tmp_path)
        loss = _mlp(act="relu")
        target = _op_output(pt.default_main_program(), "relu")
        FLAGS.chaos = True
        FLAGS.chaos_nan_var = target
        FLAGS.check_numerics = "locate"

        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        wd = Watchdog(action="dump")
        mon = monitor.StepMonitor(name="numerics_e2e", watchdog=wd)
        mon.step()  # arm the timer
        (lv,) = exe.run(feed=_feed(), fetch_list=[loss])
        mon.step(loss=float(np.asarray(lv).ravel()[0]))
        mon.close()

        assert [t.kind for t in wd.trips] == ["nan_loss"]
        dumps = sorted(tmp_path.glob("flight-*-watchdog.jsonl"))
        assert len(dumps) == 1
        hdr = json.loads(open(dumps[0]).readline())
        assert hdr["trip"] == "nan_loss"
        num = hdr["numerics"]
        assert num["var"] == target
        assert num["op_type"] == "relu"
        assert num["replayed"] is True
        assert num["stat"]["nonfinite"] > 0
        # the injected fault is accounted by the chaos harness
        assert chaos.injected_counts().get("nan_var", 0) > 0

    def test_summary_fallback_names_first_bad_row(self):
        """Without locate armed, the trip handler falls back to the
        already-fetched summary rows of the failing step."""
        FLAGS.monitor = True
        loss = _mlp(act="relu", lr=1.0)
        prog = pt.default_main_program()
        target = _op_output(prog, "relu")
        FLAGS.chaos = True
        FLAGS.chaos_nan_var = target
        FLAGS.check_numerics = "summary"
        anum.instrument_program(prog, "summary")

        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        exe.run(feed=_feed(), fetch_list=[loss])
        v = mnum.handle_nan_trip(step=1)
        assert v is not None and v["replayed"] is False
        assert v["stat"]["nonfinite"] > 0
        # grad rows downstream of the poisoned relu are non-finite
        assert mnum.last_summary()["grad_nonfinite"] > 0


# ---------------------------------------------------------------------------
# composition: recompute, run_accumulated, run_steps, pipeline stages
# ---------------------------------------------------------------------------


class TestComposition:
    def test_compose_with_recompute(self):
        from paddle_tpu import memory

        loss = _mlp(act="tanh")
        prog = pt.default_main_program()
        twin = prog.clone()
        memory.apply_recompute(prog, ["x", "y"], fetch_names=[loss.name],
                               batch_size=8)
        anum.instrument_program(prog, "summary")

        exe = pt.Executor(pt.CPUPlace())
        scope_a, scope_b = pt.Scope(), pt.Scope()
        exe.run(pt.default_startup_program(), scope=scope_a)
        exe.run(pt.default_startup_program(), scope=scope_b)
        for n in ("w1", "b1", "w2", "b2"):
            scope_b.set_var(n, np.asarray(scope_a.find_var(n)).copy())
        feed = _feed(bs=8)
        FLAGS.monitor = True
        (la,) = exe.run(twin, feed=feed, scope=scope_a, fetch_list=[loss])
        (lb,) = exe.run(prog, feed=feed, scope=scope_b, fetch_list=[loss])
        np.testing.assert_allclose(la, lb, rtol=1e-6)  # math untouched
        assert mnum.last_summary() is not None

    def test_run_accumulated_splits_stats_by_role(self):
        loss = _mlp(act="tanh")
        prog = pt.default_main_program()
        anum.instrument_program(prog, "summary")
        # grad rows ride the non-Optimize prefix; update/weight rows ride
        # the Optimize suffix — both packs must exist for the role split
        assert prog._numerics_stats_vars == [anum.STATS_VAR,
                                             anum.STATS_OPT_VAR]
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        FLAGS.monitor = True
        k, bs = 3, 4
        rng = np.random.RandomState(7)
        feed = {"x": rng.randn(k, bs, 8).astype("float32"),
                "y": rng.randn(k, bs, 1).astype("float32")}
        outs = exe.run_accumulated(prog, feed=feed, fetch_list=[loss])
        assert len(outs) == 1  # stats stripped
        assert outs[0].shape[0] == k  # prefix fetch: one slice per micro
        summ = mnum.last_summary()
        assert summ is not None
        assert set(summ["groups"]) == {"w1", "b1", "w2", "b2"}

    def test_run_steps_combines_stacked_stats(self):
        loss = _mlp(act="tanh")
        prog = pt.default_main_program()
        anum.instrument_program(prog, "summary")
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        FLAGS.monitor = True
        steps, bs = 3, 4
        rng = np.random.RandomState(11)
        feed = {"x": rng.randn(steps, bs, 8).astype("float32"),
                "y": rng.randn(steps, bs, 1).astype("float32")}
        outs = exe.run_steps(prog, feed=feed, fetch_list=[loss])
        assert len(outs) == 1 and outs[0].shape[0] == steps
        summ = mnum.last_summary()
        assert summ is not None and summ["grad_nonfinite"] == 0
        assert summ["grad_norm"] > 0

    def test_pipeline_stage_programs_instrument_clean(self):
        from paddle_tpu.analysis import verify_program
        from paddle_tpu.parallel.pipeline import split_program

        _mlp(act="tanh", lr=0.1)
        prog = pt.default_main_program()
        stages = split_program(prog, ["x", "y"], n_stages=2)
        for st in stages:
            rep = anum.instrument_program(st.program, "locate")
            assert rep["rows"] > 0
            feeds = (st.feeds + [n for n, _, _ in st.fwd_inputs]
                     + [n for n, _, _ in st.bwd_inputs] + st.bwd_feeds)
            fetch = ([n for n, _, _ in st.fwd_outputs]
                     + [n for n, _, _ in st.bwd_outputs]
                     + list(st.program._numerics_stats_vars))
            findings = verify_program(st.program, feed_names=feeds,
                                      fetch_names=fetch, check_dead=True)
            assert findings == [], (st.index, [str(f) for f in findings])


# ---------------------------------------------------------------------------
# amp: loss scaler + overflow accounting
# ---------------------------------------------------------------------------


class TestAmpOverflow:
    def test_loss_scaler_policy(self):
        from paddle_tpu import amp

        s = amp.LossScaler(init_scale=1024.0, growth_factor=2.0,
                           backoff_factor=0.5, growth_interval=3)
        assert s.update(False) == 1024.0
        assert s.update(False) == 1024.0
        assert s.update(False) == 2048.0  # grew after 3 good steps
        assert s.update(True) == 1024.0   # halved on overflow
        assert s.good_steps == 0 and s.overflow_steps == 1
        s2 = amp.LossScaler(init_scale=2.0, backoff_factor=0.5,
                            min_scale=1.0)
        s2.update(True)
        assert s2.update(True) == 1.0  # clamped at min_scale

    def test_overflow_counter_and_scale_backoff(self):
        from paddle_tpu import amp

        FLAGS.monitor = True
        loss = _mlp(act="relu")
        prog = pt.default_main_program()
        target = _op_output(prog, "relu")
        FLAGS.chaos = True
        FLAGS.chaos_nan_var = target
        anum.instrument_program(prog, "summary")
        scaler = amp.LossScaler(init_scale=1024.0, backoff_factor=0.5)
        amp.set_loss_scaler(scaler)

        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        exe.run(feed=_feed(), fetch_list=[loss])

        reg = monitor.default_registry()
        over = [n for n in reg.names() if n.startswith("amp.overflow.")]
        assert over, "no per-group overflow counter"
        assert scaler.scale == 512.0  # backoff applied this step
        assert reg.get("amp.loss_scale").value == 512.0
        evs = flight.default_recorder().events(kind="amp.overflow")
        assert evs and evs[-1]["nonfinite"] > 0


# ---------------------------------------------------------------------------
# trace_report surfaces the verdict
# ---------------------------------------------------------------------------


def test_trace_report_numerics_section():
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import trace_report

    doc = {"traceEvents": [], "flight": {
        "header": {"numerics": {
            "step": 6, "first_bad_op": "relu@block0:op2",
            "op_type": "relu", "var": "fc_0.tmp_2", "replayed": True,
            "stat": {"nonfinite": 64.0, "abs_max": 0.0,
                     "abs_mean": 0.0, "l2": 0.0}}},
        "events": [{"kind": "numerics.summary", "grad_norm": 3.5,
                    "grad_nonfinite": 0, "nonfinite_rows": 0,
                    "groups": 4}],
    }}
    text = trace_report.report(doc, 5)
    assert "Numerics" in text
    assert "relu@block0:op2" in text
    assert "fc_0.tmp_2" in text
    assert "grad_norm=3.5" in text
