"""Autoregressive generation tier (ROADMAP item 2: KV-cache +
flash-decode + executor-driven per-token programs).

  kv_cache.KVCache       ring-buffer cache contract on the executor's
                         donated rw-state machinery
  sampler.GenerationSession
                         host drivers: greedy / temperature / top-k /
                         beam, one compiled decode program per token
  models/transformer.py build_generation_programs
                         the prefill+decode program pair
  serving/generation.py  continuous token-level batching of decode steps
"""

from .kv_cache import KVCache  # noqa: F401
from .sampler import (  # noqa: F401
    GenerationSession,
    build_transformer_session,
)
