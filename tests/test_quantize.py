"""QAT fake quantization ops + transpiler (reference:
operators/fake_quantize_op.cc, contrib/quantize/quantize_transpiler.py:81)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.contrib.quantize import QuantizeTranspiler

from op_test import OpTest

rng = np.random.RandomState(9)


class TestFakeQuantizeAbsMax(OpTest):
    op_type = "fake_quantize_abs_max"

    def test_output(self):
        x = rng.uniform(-4, 4, (6, 5)).astype("float32")
        scale = np.abs(x).max()
        r = 127.0
        q = np.clip(np.round(x / scale * r), -r, r).astype("float32")
        self.check_output(
            {"X": x},
            {"Out": q, "OutScale": np.array([scale], "float32")},
            attrs={"bit_length": 8},
        )

    def test_grad_is_straight_through(self):
        x = rng.uniform(-2, 2, (4, 3)).astype("float32")
        # STE: d mean(sum(Out)) / dX ~= range/scale * 1/n per element, the
        # same as differentiating the un-rounded base — finite differences
        # of the rounded fwd would be 0/spiky, so compare analytic grads of
        # quant against the linear op X * r / scale instead.
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import registry

        lower = registry.lookup("fake_quantize_abs_max").lower

        class Ctx:
            is_test = False

            def attr(self, name, default=None):
                return {"bit_length": 8}.get(name, default)

        def f(xv):
            return lower(Ctx(), {"X": [xv]})["Out"][0].sum()

        g = jax.grad(f)(jnp.asarray(x))
        scale = np.abs(x).max()
        np.testing.assert_allclose(
            np.asarray(g), np.full_like(x, 127.0 / scale), rtol=1e-4)


class TestFakeDequantize(OpTest):
    op_type = "fake_dequantize_max_abs"

    def test_output(self):
        x = rng.uniform(-127, 127, (6, 5)).astype("float32")
        scale = np.array([3.7], "float32")
        self.check_output(
            {"X": x, "Scale": scale},
            {"Out": x * 3.7 / 127.0},
            attrs={"max_range": 127.0},
        )


def test_quantize_transpiler_qat_trains():
    """conv+fc net: transpile -> fake ops present -> trains, and QAT logits
    stay close to the fp32 twin at 8 bits."""
    img = layers.data(name="img", shape=[1, 8, 8], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    conv = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                         act="relu")
    flat = layers.reshape(conv, [-1, 4 * 8 * 8])
    logits = layers.fc(flat, size=3)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(
            logits=logits, label=layers.reshape(label, [-1, 1])))

    t = QuantizeTranspiler()
    n = t.training_transpile()
    assert n == 4, n  # conv Input+Filter, mul X+Y

    ops = [op.type for op in pt.default_main_program().global_block().ops]
    assert ops.count("fake_quantize_abs_max") == 2          # two weights
    assert ops.count("fake_quantize_moving_average_abs_max") == 2
    assert ops.count("fake_dequantize_max_abs") == 4

    pt.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    def batch(n=16):
        lab = rng.randint(0, 3, (n, 1)).astype("int64")
        x = rng.randn(n, 1, 8, 8).astype("float32") + lab[:, :, None, None]
        return {"img": x, "label": lab}

    losses = []
    for _ in range(25):
        (lv,) = exe.run(feed=batch(), fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])

    # moving-average scale state actually updated
    scope = pt.global_scope()
    scale_vars = [
        v.name
        for v in pt.default_main_program().list_vars()
        if ".quant_scale" in v.name and "@GRAD" not in v.name
    ]
    assert scale_vars
    for nm in scale_vars:
        assert float(np.asarray(scope.find_var(nm)).reshape(-1)[0]) > 0.001


def test_qat_matches_fp32_closely():
    """8-bit fake quantization shouldn't move a small net's outputs much."""
    def build():
        img = layers.data(name="img", shape=[6], dtype="float32")
        out = layers.fc(img, size=4)
        return out

    # fp32 twin
    prog_a, st_a = pt.Program(), pt.Program()
    from paddle_tpu.core import framework as fw
    with fw.guard_unique_name():
        with pt.program_guard(prog_a, st_a):
            out_a = build()
    prog_b, st_b = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(prog_b, st_b):
            out_b = build()
            QuantizeTranspiler(
                activation_quantize_type="abs_max"
            ).training_transpile(prog_b, st_b)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(st_a)  # same names -> shared scope params
    x = rng.uniform(-1, 1, (5, 6)).astype("float32")
    (a,) = exe.run(prog_a, feed={"img": x}, fetch_list=[out_a])
    (b,) = exe.run(prog_b, feed={"img": x}, fetch_list=[out_b])
    a, b = np.asarray(a), np.asarray(b)
    assert np.max(np.abs(a - b)) < 0.05 * max(1.0, np.max(np.abs(a)))
