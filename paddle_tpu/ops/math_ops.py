"""Dense math + elementwise + activation ops.

Capability parity with reference op families (paddle/fluid/operators/
matmul_op.cc, mul_op.cc, elementwise/*, activation_op.cc, scale_op.cc,
sum_op.cc, clip_op.cc).  TPU-first: every op is one pure JAX lowering; XLA
fuses elementwise chains into matmul epilogues on the MXU/VPU, which is what
the reference needed hand-written fused kernels for (fused_elemwise_activation
et al.).
"""

from __future__ import annotations

import numpy as np

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# Elementwise binary ops with Paddle broadcast semantics
# (reference: operators/elementwise/elementwise_op_function.h — Y's shape is a
# contiguous subsequence of X's dims starting at `axis`)
# ---------------------------------------------------------------------------


def _broadcast_y(x, y, axis):
    if x.shape == y.shape:
        return y
    if x.ndim == y.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + tuple(y.shape) + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _ew_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is not None:
        ctx.set_output("Out", xs, ctx.input_dtype("X"))


def _register_elementwise(name, fn):
    def lower(ctx, ins, _fn=fn, _name=name):
        from ..core.selected_rows import SelectedRows

        x = ins["X"][0]
        y = ins["Y"][0]
        if isinstance(x, SelectedRows):
            # row-sparse grad x scalar (e.g. global-norm clip scale): apply
            # to the rows; any non-scalar rhs would touch untouched rows
            if _name in ("elementwise_mul", "elementwise_div") and (
                not hasattr(y, "shape") or int(np.prod(y.shape)) == 1
            ):
                ys = y.reshape(()) if hasattr(y, "reshape") else y
                return {"Out": [SelectedRows(x.ids, _fn(x.rows, ys), x.height)]}
            raise TypeError(
                f"{_name} on SelectedRows supports only scalar rhs; got "
                f"shape {getattr(y, 'shape', None)}"
            )
        yb = _broadcast_y(x, y, ctx.attr("axis", -1))
        return {"Out": [_fn(x, yb)]}

    lower.__name__ = f"lower_{name}"
    register(name, infer_shape=_ew_infer)(lower)


_jnp_ops = None


def _install_elementwise():
    import jax.numpy as jnp

    _register_elementwise("elementwise_add", lambda x, y: x + y)
    _register_elementwise("elementwise_sub", lambda x, y: x - y)
    _register_elementwise("elementwise_mul", lambda x, y: x * y)
    _register_elementwise("elementwise_div", lambda x, y: x / y)
    _register_elementwise("elementwise_max", jnp.maximum)
    _register_elementwise("elementwise_min", jnp.minimum)
    _register_elementwise("elementwise_pow", jnp.power)
    _register_elementwise(
        "elementwise_mod",
        lambda x, y: jnp.mod(x, y) if jnp.issubdtype(x.dtype, jnp.integer) else jnp.fmod(x, y),
    )
    _register_elementwise("elementwise_floordiv", lambda x, y: jnp.floor_divide(x, y))


# ---------------------------------------------------------------------------
# Unary activations (reference: operators/activation_op.cc ~30 kernels)
# ---------------------------------------------------------------------------


def _register_unary(name, fn):
    def lower(ctx, ins, _fn=fn):
        return {"Out": [_fn(ins["X"][0], ctx)]}

    lower.__name__ = f"lower_{name}"
    register(name, infer_shape=_ew_infer)(lower)


def _install_unary():
    import jax
    import jax.numpy as jnp
    from jax import nn as jnn

    u = _register_unary
    u("relu", lambda x, c: jnn.relu(x))
    u("relu6", lambda x, c: jnp.clip(x, 0.0, c.attr("threshold", 6.0)))
    u("sigmoid", lambda x, c: jax.nn.sigmoid(x))
    u("logsigmoid", lambda x, c: jax.nn.log_sigmoid(x))
    u("tanh", lambda x, c: jnp.tanh(x))
    u("tanh_shrink", lambda x, c: x - jnp.tanh(x))
    u("sqrt", lambda x, c: jnp.sqrt(x))
    u("rsqrt", lambda x, c: jax.lax.rsqrt(x))
    u("abs", lambda x, c: jnp.abs(x))
    u("ceil", lambda x, c: jnp.ceil(x))
    u("floor", lambda x, c: jnp.floor(x))
    u("round", lambda x, c: jnp.round(x))
    u("reciprocal", lambda x, c: 1.0 / x)
    u("log", lambda x, c: jnp.log(x))
    u("square", lambda x, c: jnp.square(x))
    u("exp", lambda x, c: jnp.exp(x))
    u("sin", lambda x, c: jnp.sin(x))
    u("cos", lambda x, c: jnp.cos(x))
    u(
        "gelu",
        lambda x, c: jnn.gelu(x, approximate=bool(c.attr("approximate", False))),
    )
    u(
        "leaky_relu",
        lambda x, c: jnn.leaky_relu(x, negative_slope=c.attr("alpha", 0.02)),
    )
    u("elu", lambda x, c: jnn.elu(x, alpha=c.attr("alpha", 1.0)))
    u(
        "soft_relu",
        lambda x, c: jnp.log1p(
            jnp.exp(jnp.clip(x, -c.attr("threshold", 40.0), c.attr("threshold", 40.0)))
        ),
    )
    u("softplus", lambda x, c: jnn.softplus(x))
    u("softsign", lambda x, c: x / (1 + jnp.abs(x)))
    u(
        "softshrink",
        lambda x, c: jnp.where(
            x > c.attr("lambda", 0.5),
            x - c.attr("lambda", 0.5),
            jnp.where(x < -c.attr("lambda", 0.5), x + c.attr("lambda", 0.5), 0.0),
        ),
    )
    u(
        "hard_sigmoid",
        lambda x, c: jnp.clip(
            c.attr("slope", 0.2) * x + c.attr("offset", 0.5), 0.0, 1.0
        ),
    )
    u(
        "thresholded_relu",
        lambda x, c: jnp.where(x > c.attr("threshold", 1.0), x, 0.0),
    )
    u(
        "hard_shrink",
        lambda x, c: jnp.where(jnp.abs(x) > c.attr("threshold", 0.5), x, 0.0),
    )
    u(
        "brelu",
        lambda x, c: jnp.clip(x, c.attr("t_min", 0.0), c.attr("t_max", 24.0)),
    )
    u(
        "swish",
        lambda x, c: x * jax.nn.sigmoid(c.attr("beta", 1.0) * x),
    )
    u("stanh", lambda x, c: c.attr("scale_b", 1.7159) * jnp.tanh(c.attr("scale_a", 2.0 / 3.0) * x))
    u(
        "pow",
        lambda x, c: jnp.power(x, c.attr("factor", 1.0)),
    )
    u("logical_not", lambda x, c: jnp.logical_not(x))


# ---------------------------------------------------------------------------
# matmul / mul / scale / sum / clip
# ---------------------------------------------------------------------------


def _matmul_infer(ctx):
    xs, ys = ctx.input_shape("X"), ctx.input_shape("Y")
    if xs is None or ys is None:
        return
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    xs = list(xs)
    ys = list(ys)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if ty:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    # -1 is the dynamic-dim placeholder — only flag a mismatch when both
    # contraction dims are statically known
    if xs[-1] != ys[-2] and xs[-1] >= 0 and ys[-2] >= 0:
        raise ValueError(
            f"matmul contraction dims mismatch: X{tuple(xs)} @ Y{tuple(ys)}"
        )
    batch = xs[:-2] if len(xs) > len(ys) else ys[:-2]
    ctx.set_output("Out", tuple(batch) + (xs[-2], ys[-1]), ctx.input_dtype("X"))


@register("matmul", infer_shape=_matmul_infer)
def lower_matmul(ctx, ins):
    """Batched matmul w/ transpose + alpha (reference: matmul_op.cc).
    Maps directly to the MXU via dot_general."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    if ctx.attr("transpose_X", False):
        axes = list(range(x.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        x = jnp.transpose(x, axes)
    if ctx.attr("transpose_Y", False):
        axes = list(range(y.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        y = jnp.transpose(y, axes)
    out = jnp.matmul(x, y)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


def _mul_infer(ctx):
    xs, ys = ctx.input_shape("X"), ctx.input_shape("Y")
    if xs is None or ys is None:
        return
    nx = ctx.attr("x_num_col_dims", 1)
    ny = ctx.attr("y_num_col_dims", 1)
    ctx.set_output("Out", tuple(xs[:nx]) + tuple(ys[ny:]), ctx.input_dtype("X"))


@register("mul", infer_shape=_mul_infer)
def lower_mul(ctx, ins):
    """2D matmul with leading-dim flattening (reference: mul_op.cc;
    x_num_col_dims semantics)."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    nx = ctx.attr("x_num_col_dims", 1)
    ny = ctx.attr("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:nx])), -1))
    y2 = y.reshape((int(np.prod(ys[:ny])), -1))
    out = x2 @ y2
    return {"Out": [out.reshape(tuple(xs[:nx]) + tuple(ys[ny:]))]}


@register("scale", infer_shape=_ew_infer)
def lower_scale(ctx, ins):
    """out = scale * (x + bias) or scale * x + bias (reference: scale_op.cc;
    also accepts SelectedRows like the reference kernel — bias must be 0,
    otherwise untouched rows would change)."""
    from ..core.selected_rows import SelectedRows

    x = ins["X"][0]
    scale = ctx.attr("scale", 1.0)
    bias = ctx.attr("bias", 0.0)
    if isinstance(x, SelectedRows):
        if bias != 0.0:
            raise TypeError("scale(SelectedRows) requires bias == 0")
        return {"Out": [SelectedRows(x.ids, x.rows * scale, x.height)]}
    if ctx.attr("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


def _sum_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is not None:
        ctx.set_output("Out", xs, ctx.input_dtype("X"))


@register("sum", infer_shape=_sum_infer)
def lower_sum(ctx, ins):
    """Add N tensors (reference: sum_op.cc).  SelectedRows operands follow
    math/selected_rows_functor.h: all-sparse sums concatenate (duplicates
    are legal and merged lazily at the consumer); mixed dense+sparse sums
    scatter-add the sparse parts into the dense sum."""
    from ..core.selected_rows import SelectedRows

    vals = [v for v in ins["X"] if v is not None]
    sparse = [v for v in vals if isinstance(v, SelectedRows)]
    dense = [v for v in vals if not isinstance(v, SelectedRows)]
    if sparse and not dense:
        return {"Out": [SelectedRows.concat(sparse)]}
    if not dense:
        raise ValueError("sum op with no inputs")
    out = dense[0]
    for v in dense[1:]:
        out = out + v
    for s in sparse:
        out = s.add_to(out)
    return {"Out": [out]}


def _merged_sr(x):
    """Reference clip kernels merge duplicate SelectedRows rows before any
    nonlinear elementwise op (clip.py merge_selected_rows): (a+b) must be
    clipped once, not clip(a)+clip(b)."""
    from ..core.selected_rows import SelectedRows

    uids, mrows = x.merged()
    return SelectedRows(uids, mrows, x.height)


@register("clip", infer_shape=_ew_infer)
def lower_clip(ctx, ins):
    from ..core.selected_rows import SelectedRows

    jnp = _jnp()
    x = ins["X"][0]
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    if isinstance(x, SelectedRows):
        m = _merged_sr(x)
        return {"Out": [SelectedRows(m.ids, jnp.clip(m.rows, lo, hi), m.height)]}
    return {"Out": [jnp.clip(x, lo, hi)]}


@register("clip_by_norm", infer_shape=_ew_infer)
def lower_clip_by_norm(ctx, ins):
    from ..core.selected_rows import SelectedRows

    jnp = _jnp()
    x = ins["X"][0]
    max_norm = ctx.attr("max_norm", 1.0)
    if isinstance(x, SelectedRows):
        m = _merged_sr(x)
        norm = jnp.sqrt(jnp.sum(jnp.square(m.rows)))
        s = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return {"Out": [SelectedRows(m.ids, m.rows * s, m.height)]}
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * scale]}


@register("squared_l2_norm")
def lower_squared_l2_norm(ctx, ins):
    from ..core.selected_rows import SelectedRows

    jnp = _jnp()
    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        _, mrows = x.merged()
        return {"Out": [jnp.sum(jnp.square(mrows)).reshape((1,))]}
    return {"Out": [jnp.sum(jnp.square(x)).reshape((1,))]}


def _install():
    _install_elementwise()
    _install_unary()


_install()
