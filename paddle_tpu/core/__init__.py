from . import framework, registry, executor, backward  # noqa: F401
