"""Dataset cache/download helpers (reference: python/paddle/dataset/common.py
— DATA_HOME, download with md5 check, cached unpacking)."""

from __future__ import annotations

import hashlib
import os
import shutil

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset")
)


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def _urlretrieve(url, tmp):
    """Seam for tests (flaky fake openers monkeypatch this)."""
    import urllib.request

    urllib.request.urlretrieve(url, tmp)


def download(url, module_name, md5sum, save_name=None, retries=3):
    """Download-with-cache (reference common.py:download), hardened:
    transient fetch errors retry with jittered backoff (utils/retry.py),
    stale partial `.part` files from a killed earlier download are
    cleaned up, and an md5 mismatch triggers a RE-DOWNLOAD (a corrupt
    fetch is just another transient fault) instead of raising on the
    first bad copy.  In zero-egress environments, place the file at the
    cache path manually; a missing file raises with that path in the
    message."""
    from ..testing import chaos
    from ..utils.retry import RetryError, retry_call

    dirname = must_mkdirs(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename) and (not md5sum or md5file(filename) == md5sum):
        return filename
    tmp = filename + ".part"

    def fetch():
        if os.path.exists(tmp):
            os.remove(tmp)  # partial leftovers of a killed download
        chaos.maybe_io_error("dataset.download")
        _urlretrieve(url, tmp)
        if md5sum and md5file(tmp) != md5sum:
            os.remove(tmp)
            raise OSError(f"md5 mismatch for {url} (corrupt fetch)")
        shutil.move(tmp, filename)

    try:
        retry_call(fetch, retries=retries, base_delay=0.1, max_delay=5.0,
                   retry_on=(OSError, ValueError),
                   name="dataset.download")
    except Exception as e:
        cause = e.last if isinstance(e, RetryError) else e
        raise RuntimeError(
            f"cannot download {url} (offline?): {cause}. "
            f"Place the file manually at {filename}."
        ) from e
    return filename


def use_synthetic(explicit=False):
    """Whether readers should yield synthetic offline data (explicit arg,
    FLAGS_synthetic_data, or PADDLE_TPU_SYNTH_DATA=1)."""
    from ..flags import FLAGS

    return bool(
        explicit
        or FLAGS.synthetic_data
        or os.environ.get("PADDLE_TPU_SYNTH_DATA") == "1"
    )
