"""Datasets (reference: python/paddle/dataset/ — 15 auto-download+cache
datasets).  Each has a synthetic offline fallback (synthetic=True or
PADDLE_TPU_SYNTH_DATA=1) for zero-egress environments."""

from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)
