"""Generation drivers: host loops stepping the compiled program pair.

A GenerationSession owns (prefill, decode[, hyps]) programs built by
models/transformer.py build_generation_programs, the cache scope state,
and one Executor.  Every generated token is ONE Executor.run of the
decode program with FIXED feed shapes — after prefill + the first decode
step the executor's compile cache never grows (asserted in
tests/test_generation.py and recorded by bench.py --model decode).

Strategies: greedy / temperature / top-k ride the sample_token op inside
the decode program (greedy programs compile key-free and are
bit-deterministic); beam search rides the existing beam_search op
semantics — the per-token program runs one cached decoder step, the
dense top-k beam step, and the kv_cache_reorder parent gather, and the
final hypotheses backtrack through beam_search_decode.

FLAGS.kv_cache off swaps the decode program for the full-prefix
recompute oracle (token-identical outputs, O(T²) per token) — the A/B
baseline bench.py records.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _pos_ids(batch, seq_len):
    return np.tile(np.arange(seq_len, dtype=np.int64)[None, :, None],
                   (batch, 1, 1))


class GenerationSession:
    """Host driver for one generation program set.

    scope/executor default to fresh private instances; pass a trained
    scope (parameter names match `transformer(...)`) to generate from a
    trained model.  `init_params()` runs the startup program for
    randomly-initialized smoke use."""

    def __init__(self, programs, scope=None, place=None, executor=None):
        from ..core import executor as ex

        self.p = programs
        self.scope = scope if scope is not None else ex.Scope()
        self.exe = executor or ex.Executor(place or ex.default_place())
        self._allocate()

    # -- state -----------------------------------------------------------
    def _allocate(self):
        """Zero-fill the cache / aux scope state so the scope signature
        (part of the executor compile key) is stable from run one."""
        import jax.numpy as jnp

        p = self.p
        self._paged_dynamic_only = False
        if p.kv_cache:
            if getattr(p, "paged", False) and any(
                    c.num_blocks < c.batch * c.max_blocks
                    for c in (p.self_cache, p.cross_cache)):
                # FLAGS_kv_cache_blocks sized the pool BELOW full static
                # occupancy — the whole point of paging (serve by HBM
                # bytes, not slot count), but only the serving batcher
                # maps blocks per request; static identity tables can't
                # exist, so arm dynamic mode and refuse the one-shot
                # generate() driver (it would read trap rows).
                p.self_cache.reset_dynamic(self.scope)
                p.cross_cache.reset_dynamic(self.scope)
                self._paged_dynamic_only = True
            else:
                p.self_cache.allocate(self.scope)
                p.cross_cache.allocate(self.scope)
            if getattr(p, "self_feed_token", False):
                # greedy self-feed state (FLAGS_fused_decode_step):
                # the decode program reads/latches these in-graph; the
                # prefill's active mask resets joining lanes, so a
                # BOS/zero fill here only pins the scope signature
                import jax

                i64 = jax.dtypes.canonicalize_dtype(np.int64)
                self.scope.set_var(
                    p.last_tok_name,
                    jnp.full((p.lanes, 1), p.bos_id, i64))
                self.scope.set_var(
                    p.finished_name, jnp.zeros((p.lanes,), jnp.int32))
        else:
            self.scope.set_var(
                p.enc_out_name,
                jnp.zeros((p.lanes, p.src_seq_len, p.d_model),
                          jnp.float32))
            self.scope.set_var(
                p.src_bias_name,
                jnp.zeros((p.lanes, 1, 1, p.src_seq_len), jnp.float32))

    def init_params(self):
        self.exe.run(self.p.startup, scope=self.scope)

    @property
    def compile_count(self) -> int:
        """Compiled-signature count (the flat-across-tokens invariant)."""
        return len(self.exe._cache)

    # -- steps -----------------------------------------------------------
    def prefill(self, src_word, src_pos=None, active=None):
        """Run the prefill program: encoder -> cross cache (or enc_out
        aux state).  src_word [b, Ts, 1] int64; active [b] 0/1 selects
        which cache slots (re)join — continuous batching's late-join
        mask; default all.  Returns per-sequence source lengths."""
        p = self.p
        src_word = np.asarray(src_word, np.int64)
        b = src_word.shape[0]
        if src_word.ndim == 2:
            src_word = src_word[:, :, None]
        if src_pos is None:
            src_pos = _pos_ids(b, p.src_seq_len)
        feed = {"src_word": src_word, "src_pos": np.asarray(src_pos)}
        if p.kv_cache:
            a = (np.ones((b, 1), np.float32) if active is None
                 else np.asarray(active, np.float32).reshape(b, 1))
            feed["gen_active"] = a
        (lens,) = self.exe.run(p.prefill, feed=feed,
                               fetch_list=p.prefill_fetch,
                               scope=self.scope)
        return np.asarray(lens)

    def decode_step(self, tokens, active=None, prefix=None, t=None):
        """One decode step -> next token per lane [lanes, 1] int64.

        Cached route: feed the last token (+ active mask) — unless the
        program self-feeds (greedy under FLAGS_fused_decode_step: the
        token lives in scope state and `tokens` is ignored).  Recompute
        route: feed the full host-maintained prefix buffer and the step
        index instead (tokens/active are ignored)."""
        p = self.p
        if p.kv_cache:
            a = (np.ones((p.lanes, 1), np.float32) if active is None
                 else np.asarray(active, np.float32).reshape(p.lanes, 1))
            feed = {"gen_active": a}
            if not getattr(p, "self_feed_token", False):
                feed["gen_token"] = np.asarray(
                    tokens, np.int64).reshape(p.lanes, 1)
        else:
            feed = {"gen_prefix":
                    np.asarray(prefix, np.int64).reshape(
                        p.lanes, p.t_buf, 1),
                    "gen_t": np.asarray([t], np.int64)}
        (nxt,) = self.exe.run(p.decode, feed=feed,
                              fetch_list=p.decode_fetch, scope=self.scope)
        return np.asarray(nxt).reshape(p.lanes)

    # -- drivers ---------------------------------------------------------
    def generate(self, src_word, src_pos=None,
                 max_tokens: Optional[int] = None):
        """Greedy/sampled generation: returns (tokens [b, n] int64 —
        eos-padded past each sequence's end — , n_steps run).  Host loop:
        prefill once, then one decode-program run per token with early
        exit once every sequence has emitted eos."""
        p = self.p
        assert p.beam_size is None, "use generate_beam for beam programs"
        if self._paged_dynamic_only:
            raise RuntimeError(
                "paged KV pool is smaller than batch*max_blocks (dynamic "
                "serving mode): drive it through ContinuousBatcher, which "
                "maps blocks per request — generate() needs the static "
                "identity tables")
        max_tokens = min(max_tokens or p.max_out_len, p.max_out_len)
        src_word = np.asarray(src_word, np.int64)
        b = src_word.shape[0]
        if b != p.batch_size:
            raise ValueError(
                f"generate: got {b} rows, programs are compiled for "
                f"batch {p.batch_size}")
        self.prefill(src_word, src_pos)
        tokens = np.full((b,), p.bos_id, np.int64)
        finished = np.zeros((b,), bool)
        if not p.kv_cache:
            prefix = np.full((b, p.t_buf), p.bos_id, np.int64)
        out = []
        steps = 0
        for t in range(max_tokens):
            if p.kv_cache:
                nxt = self.decode_step(tokens)
            else:
                nxt = self.decode_step(None, prefix=prefix, t=t)
            # sequences already finished keep emitting eos (and keep
            # feeding eos — both routes see identical token streams, so
            # the flag A/B stays token-identical by construction)
            nxt = np.where(finished, p.eos_id, nxt)
            out.append(nxt.copy())
            finished |= nxt == p.eos_id
            steps += 1
            if finished.all():
                break
            tokens = nxt
            if not p.kv_cache and t + 1 < p.t_buf:
                prefix[:, t + 1] = nxt
        return np.stack(out, axis=1), steps

    def generate_beam(self, src_word, src_pos=None,
                      max_tokens: Optional[int] = None):
        """Beam generation: returns (sentence_ids [b, beam, T] int64
        eos-padded, sentence_scores [b, beam]).  Output-parity with the
        build_decoder While program is asserted in tests."""
        p = self.p
        assert p.beam_size is not None, "programs were built without beams"
        b, k = p.batch_size, p.beam_size
        max_tokens = min(max_tokens or p.max_out_len, p.max_out_len)
        self.prefill(np.asarray(src_word, np.int64), src_pos)
        pre_ids = np.full((b, k), p.bos_id, np.int64)
        pre_scores = np.full((b, k), -1e9, np.float32)
        pre_scores[:, 0] = 0.0
        parents_flat = np.arange(b * k, dtype=np.int64)
        ids_steps, parent_steps = [], []
        for _ in range(max_tokens):
            (sel_ids, sel_scores, next_flat) = self.exe.run(
                p.decode,
                feed={"gen_pre_ids": pre_ids,
                      "gen_pre_scores": pre_scores,
                      "gen_parents":
                      parents_flat.reshape(b * k, 1)},
                fetch_list=p.decode_fetch, scope=self.scope)
            sel_ids = np.asarray(sel_ids)
            sel_scores = np.asarray(sel_scores).astype(np.float32)
            next_flat = np.asarray(next_flat).reshape(b * k)
            ids_steps.append(sel_ids)
            parent_steps.append((next_flat % k).reshape(b, k))
            pre_ids, pre_scores = sel_ids, sel_scores
            parents_flat = next_flat
            if (sel_ids == p.eos_id).all():
                break
        # pad to the compiled [max_out_len] hyps shape: eos continuations
        # under identity parents backtrack exactly like NumSteps masking
        identity = np.broadcast_to(np.arange(k, dtype=np.int64), (b, k))
        while len(ids_steps) < p.max_out_len:
            ids_steps.append(np.full((b, k), p.eos_id, np.int64))
            parent_steps.append(identity.copy())
        sent, scores = self.exe.run(
            p.hyps,
            feed={"gen_steps_ids": np.stack(ids_steps, axis=0),
                  "gen_steps_parents": np.stack(parent_steps, axis=0),
                  "gen_final_scores": pre_scores},
            fetch_list=p.hyps_fetch, scope=self.scope)
        return np.asarray(sent), np.asarray(scores)


def build_transformer_session(scope=None, place=None, executor=None,
                              **model_kw) -> GenerationSession:
    """Convenience: build_generation_programs + GenerationSession."""
    from ..models.transformer import build_generation_programs

    return GenerationSession(build_generation_programs(**model_kw),
                            scope=scope, place=place, executor=executor)
