"""Weight-decay regularizers appended as grad ops
(reference: python/paddle/fluid/regularizer.py)."""

from __future__ import annotations

from .core import framework as fw
from .layer_helper import LayerHelper


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(
            "scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        # d|p|/dp = sign(p) = p / (|p| + eps)
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(
            "elementwise_div",
            inputs={"X": [param], "Y": [_abs_plus_eps(helper, param)]},
            outputs={"Out": [sign]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        block.append_op(
            "scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        return decay


def _abs_plus_eps(helper, param):
    a = helper.create_variable_for_type_inference(param.dtype)
    helper.append_op("abs", inputs={"X": [param]}, outputs={"Out": [a]})
    b = helper.create_variable_for_type_inference(param.dtype)
    helper.append_op(
        "scale", inputs={"X": [a]}, outputs={"Out": [b]}, attrs={"bias": 1e-12}
    )
    return b


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Add decay terms onto grads (reference: regularizer.py
    append_regularization_ops)."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularization_term = None
        reg = getattr(param, "regularizer", None) or regularization
        if reg is not None:
            regularization_term = reg(param, grad, grad.block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        helper = LayerHelper("regularized_grad")
        new_grad = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op(
            "sum",
            inputs={"X": [grad, regularization_term]},
            outputs={"Out": [new_grad]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward},
        )
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
