"""OpTest harness — parity with the reference's
python/paddle/fluid/tests/unittests/op_test.py: run a single op through the
executor, check outputs against a numpy reference, and check analytic
gradients (append_backward over a tiny program) against numeric finite
differences (reference op_test.py:43 get_numeric_gradient, :425 check_grad).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core import framework as fw


def build_op_program(op_type, inputs, attrs, output_slots):
    """Build a fresh program containing just `op_type`.

    inputs: {slot: [(name, np_array)]}
    output_slots: {slot: [names]}
    Returns (program, feed_dict, out_names)
    """
    prog = fw.Program()
    startup = fw.Program()
    feed = {}
    with fw.program_guard(prog, startup):
        block = prog.global_block()
        in_spec = {}
        for slot, pairs in inputs.items():
            names = []
            for name, arr in pairs:
                arr = np.asarray(arr)
                block.create_var(
                    name=name, shape=arr.shape, dtype=str(arr.dtype), is_data=True
                )
                feed[name] = arr
                names.append(name)
            in_spec[slot] = names
        out_spec = {}
        for slot, names in output_slots.items():
            for n in names:
                block.create_var(name=n, dtype="float32")
            out_spec[slot] = list(names)
        block.append_op(op_type, inputs=in_spec, outputs=out_spec, attrs=attrs)
    return prog, feed, out_spec


class OpTest:
    """Subclass and set: op_type, inputs {slot: np or [(name, np)]},
    attrs, outputs {slot: expected np or name list}."""

    op_type: str = ""
    attrs: Dict = {}

    def _norm_inputs(self, inputs):
        out = {}
        for slot, v in inputs.items():
            if isinstance(v, list):
                out[slot] = [(n, np.asarray(a)) for n, a in v]
            else:
                out[slot] = [(slot, np.asarray(v))]
        return out

    def _out_slots(self, outputs):
        slots = {}
        for slot, v in outputs.items():
            if isinstance(v, list):
                slots[slot] = [n for n, _ in v]
            else:
                slots[slot] = [slot + "@out"]
        return slots

    def check_output(self, inputs, outputs, attrs=None, atol=1e-5, rtol=1e-5):
        attrs = attrs if attrs is not None else self.attrs
        norm_in = self._norm_inputs(inputs)
        out_slots = self._out_slots(outputs)
        prog, feed, out_spec = build_op_program(
            self.op_type, norm_in, attrs, out_slots
        )
        exe = pt.Executor(pt.CPUPlace())
        fetch = [n for ns in out_spec.values() for n in ns]
        res = exe.run(prog, feed=feed, fetch_list=fetch)
        got = dict(zip(fetch, res))
        for slot, v in outputs.items():
            if isinstance(v, list):
                for n, expected in v:
                    np.testing.assert_allclose(
                        got[n], expected, atol=atol, rtol=rtol,
                        err_msg=f"{self.op_type} output {n}",
                    )
            else:
                np.testing.assert_allclose(
                    got[slot + "@out"], v, atol=atol, rtol=rtol,
                    err_msg=f"{self.op_type} output {slot}",
                )
        return got

    def check_grad(
        self,
        inputs,
        output_slots: Dict[str, List[str]],
        grad_targets: List[str],
        loss_slot: Optional[str] = None,
        attrs=None,
        delta=1e-3,
        atol=1e-3,
        rtol=1e-2,
    ):
        """Compare analytic grads (append_backward) vs finite differences of
        mean(sum(outputs)) — mirrors reference check_grad."""
        attrs = attrs if attrs is not None else self.attrs
        norm_in = self._norm_inputs(inputs)

        def build(feed_override=None):
            prog = fw.Program()
            startup = fw.Program()
            with fw.program_guard(prog, startup):
                block = prog.global_block()
                feed = {}
                in_spec = {}
                for slot, pairs in norm_in.items():
                    names = []
                    for name, arr in pairs:
                        a = (
                            feed_override[name]
                            if feed_override and name in feed_override
                            else arr
                        )
                        block.create_var(
                            name=name, shape=a.shape, dtype=str(a.dtype),
                            is_data=name not in grad_targets,
                            stop_gradient=name not in grad_targets,
                        )
                        feed[name] = a
                        names.append(name)
                    in_spec[slot] = names
                out_spec = {}
                for slot, names in output_slots.items():
                    for n in names:
                        block.create_var(name=n, dtype="float32")
                    out_spec[slot] = list(names)
                block.append_op(self.op_type, inputs=in_spec, outputs=out_spec, attrs=attrs)
                # loss = mean over (sum of) outputs in loss_slot (or first)
                tslot = loss_slot or list(output_slots)[0]
                tnames = out_spec[tslot]
                from paddle_tpu import layers

                target = tnames[0]
                loss = layers.reduce_mean(block.var(target))
            return prog, feed, loss

        # analytic
        prog, feed, loss = build()
        with fw.program_guard(prog):
            pt.append_backward(loss)
        exe = pt.Executor(pt.CPUPlace())
        grad_names = [fw.grad_var_name(n) for n in grad_targets]
        analytic = exe.run(prog, feed=feed, fetch_list=grad_names)

        # numeric: ONE program, rerun with perturbed feeds (executor caches
        # the compiled executable across calls)
        prog2, base_feed2, loss2 = build()
        exe2 = pt.Executor(pt.CPUPlace())

        def fwd(feed_override):
            feed2 = dict(base_feed2)
            feed2.update(feed_override)
            (out,) = exe2.run(prog2, feed=feed2, fetch_list=[loss2])
            return float(np.asarray(out))

        for gname, tname, g_analytic in zip(grad_names, grad_targets, analytic):
            base = None
            for slot, pairs in norm_in.items():
                for name, arr in pairs:
                    if name == tname:
                        base = arr.astype(np.float64)
            assert base is not None
            numeric = np.zeros_like(base)
            flat = base.ravel()
            num_flat = numeric.ravel()
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + delta
                f_pos = fwd({tname: base.astype(np.float32)})
                flat[i] = orig - delta
                f_neg = fwd({tname: base.astype(np.float32)})
                flat[i] = orig
                num_flat[i] = (f_pos - f_neg) / (2 * delta)
            np.testing.assert_allclose(
                np.asarray(g_analytic),
                numeric,
                atol=atol,
                rtol=rtol,
                err_msg=f"{self.op_type} grad wrt {tname}",
            )
