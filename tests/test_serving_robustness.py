"""Overload-hardened serving (ISSUE 13): admission control + shedding,
deadline propagation, graceful drain, circuit breaker, scheduler-death
liveness, and the serving chaos kinds.

Covers the robustness tentpole + satellites: bounded queues shed with
429/Retry-After (queue-latency EWMA), expired requests are dropped
BEFORE dispatch (never reach the executor), stop()/drain() fail or
finish queued-admitted work with named 503s instead of client-timeout
hangs, the per-model circuit breaker opens on consecutive executor
failures and half-open-probes closed, /health reports `draining` and
`scheduler_dead`, a SIGTERM'd serving subprocess drains in-flight work
and exits 0 with a drain-trigger flight dump, and all of it is
zero-cost with FLAGS_monitor / FLAGS_chaos off.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.flags import FLAGS
from paddle_tpu.monitor import default_registry, flight
from paddle_tpu.monitor import serve as mserve
from paddle_tpu.serving import (
    CircuitBreaker,
    DynamicBatcher,
    InferenceServer,
    ModelConfig,
    Overloaded,
    ServingModel,
    Unavailable,
)
from paddle_tpu.testing import chaos

rng = np.random.RandomState(13)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    """Default flags, empty registry/chaos counters around every test;
    never leak the serving readiness provider."""
    FLAGS.reset()
    default_registry().reset()
    chaos.reset()
    flight.default_recorder().clear()
    yield
    mserve.set_readiness_provider(None)
    FLAGS.reset()
    default_registry().reset()
    chaos.reset()
    flight.default_recorder().clear()


def _export_fc_model(dirname, in_dim=6, out_dim=3, seed=3):
    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = seed
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=out_dim)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


@pytest.fixture(scope="module")
def fc_dir(tmp_path_factory):
    return _export_fc_model(
        str(tmp_path_factory.mktemp("robustness") / "fc"))


def _serving_model(dirname, **kw):
    kw.setdefault("buckets", "1,2,4,8")
    kw.setdefault("max_wait_ms", 5.0)
    return ServingModel(ModelConfig("m", dirname, **kw))


def _feed(n_rows=1):
    return {"x": rng.randn(n_rows, 6).astype("float32")}


# ---------------------------------------------------------------------------
# admission control: bounded queues shed with 429 + Retry-After
# ---------------------------------------------------------------------------


class TestAdmissionControl:
    def test_queue_depth_sheds_with_retry_after(self, fc_dir):
        FLAGS.monitor = True
        FLAGS.serving_max_queue_depth = 2
        b = DynamicBatcher(_serving_model(fc_dir))  # scheduler NOT started
        for _ in range(2):  # fill the bounded queue
            with pytest.raises(TimeoutError):
                b.submit(_feed(), timeout=0.01)
        with pytest.raises(Overloaded) as ei:
            b.submit(_feed(), timeout=0.01)
        assert ei.value.reason == "queue_depth"
        assert ei.value.retry_after_s > 0
        assert int(ei.value.retry_after_header) >= 1
        reg = default_registry()
        assert reg.get("serving.m.shed_total").value == 1
        assert reg.get("serving.shed_total").value == 1
        assert flight.default_recorder().events(kind="serving.shed")
        b.stop()

    def test_queue_depth_zero_is_unbounded_legacy(self, fc_dir):
        FLAGS.serving_max_queue_depth = 0
        b = DynamicBatcher(_serving_model(fc_dir))
        for _ in range(6):  # would shed at any bound; 0 = legacy queue
            with pytest.raises(TimeoutError):
                b.submit(_feed(), timeout=0.01)
        b.stop()

    def test_server_inflight_cap_sheds(self, fc_dir):
        FLAGS.monitor = True
        FLAGS.serving_max_inflight = 1
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        try:
            m = srv._models["m"]
            orig = m.run_batch

            def slow(*a, **kw):
                time.sleep(0.4)
                return orig(*a, **kw)

            m.run_batch = slow
            res = {}

            def client():
                try:
                    res["out"] = srv.submit("m", _feed(), timeout=10)
                except Exception as e:  # noqa: BLE001
                    res["err"] = e

            t = threading.Thread(target=client)
            t.start()
            deadline = time.time() + 5
            while srv._inflight < 1 and time.time() < deadline:
                time.sleep(0.005)
            with pytest.raises(Overloaded) as ei:
                srv.submit("m", _feed(), timeout=1)
            assert ei.value.reason == "inflight_cap"
            t.join(timeout=10)
            assert "out" in res, res
            assert default_registry().get(
                "serving.inflight_shed_total").value == 1
        finally:
            srv.stop()

    def test_http_429_carries_retry_after_header(self, fc_dir):
        FLAGS.serving_max_inflight = 1
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        try:
            url = f"http://127.0.0.1:{srv.port}"
            m = srv._models["m"]
            orig = m.run_batch

            def slow(*a, **kw):
                time.sleep(0.5)
                return orig(*a, **kw)

            m.run_batch = slow
            t = threading.Thread(
                target=lambda: srv.submit("m", _feed(), timeout=10))
            t.start()
            deadline = time.time() + 5
            while srv._inflight < 1 and time.time() < deadline:
                time.sleep(0.005)
            req = urllib.request.Request(
                f"{url}/v1/models/m:predict",
                data=json.dumps({"inputs": {"x": [[0.0] * 6]}}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            body = json.loads(ei.value.read())
            assert body["reason"] == "inflight_cap"
            assert body["retry_after_s"] > 0
            t.join(timeout=10)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# deadline propagation: expired requests never reach the executor
# ---------------------------------------------------------------------------


class TestDeadlinePropagation:
    def test_expired_request_dropped_before_dispatch(self, fc_dir):
        FLAGS.monitor = True
        m = _serving_model(fc_dir)
        b = DynamicBatcher(m)
        dispatched = []
        orig = m.run_batch

        def spy(precision, feed, rows, bucket, sig):
            dispatched.append(rows)
            return orig(precision, feed, rows, bucket, sig)

        m.run_batch = spy
        # queue a request whose deadline passes while the scheduler is
        # down (the stand-in for "aged out under overload")
        with pytest.raises(TimeoutError):
            b.submit(_feed(1), timeout=0.05)
        time.sleep(0.06)
        b.start()
        outs, meta = b.submit(_feed(2), timeout=10)
        b.stop()
        # only the live 2-row request was ever dispatched
        assert dispatched == [2], dispatched
        assert default_registry().get(
            "serving.m.expired_dropped_total").value == 1
        assert default_registry().get(
            "serving.expired_dropped_total").value == 1
        assert meta["request_rows"] == 2

    def test_http_timeout_s_becomes_the_deadline(self, fc_dir):
        """The request body's timeout_s rides the queued request: a
        server-side 504 (not a silent execute) when it expires."""
        FLAGS.monitor = True
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        try:
            url = f"http://127.0.0.1:{srv.port}"
            m = srv._models["m"]
            orig = m.run_batch

            def slow(*a, **kw):
                time.sleep(0.5)
                return orig(*a, **kw)

            m.run_batch = slow
            # occupy the scheduler, then send a short-deadline request
            t = threading.Thread(
                target=lambda: srv.submit("m", _feed(), timeout=10))
            t.start()
            time.sleep(0.1)
            req = urllib.request.Request(
                f"{url}/v1/models/m:predict",
                data=json.dumps({"inputs": {"x": [[0.0] * 6]},
                                 "timeout_s": 0.2}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 504
            t.join(timeout=10)
            # the expired request was dropped pre-dispatch once the
            # scheduler got to it
            deadline = time.time() + 5
            while time.time() < deadline:
                c = default_registry().get(
                    "serving.m.expired_dropped_total")
                if c is not None and c.value >= 1:
                    break
                time.sleep(0.02)
            assert default_registry().get(
                "serving.m.expired_dropped_total").value >= 1
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# stop()/drain(): queued requests fail with a NAMED 503, never a hang
# ---------------------------------------------------------------------------


class TestStopDrainsQueued:
    def test_dynamic_stop_fails_queued_with_named_503(self, fc_dir):
        m = _serving_model(fc_dir)
        orig = m.run_batch

        def slow(*a, **kw):
            time.sleep(0.3)
            return orig(*a, **kw)

        m.run_batch = slow
        b = DynamicBatcher(m, max_batch=1)
        b.start()
        outcomes = []

        def client():
            try:
                b.submit(_feed(), timeout=10)
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(e)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        t0 = time.perf_counter()
        b.stop()
        for t in threads:
            t.join(timeout=10)
        elapsed = time.perf_counter() - t0
        # waiters resolved promptly — NOT after their 10s client timeout
        assert elapsed < 5.0, elapsed
        errs = [o for o in outcomes if o != "ok"]
        assert errs, outcomes
        assert all(isinstance(e, Unavailable) for e in errs), outcomes
        assert all("stopped" in str(e) for e in errs)

    def test_stop_with_dead_scheduler_still_fails_queued(self, fc_dir):
        """stop() must drain the queue itself when the scheduler thread
        cannot (here: never started — the dead-thread stand-in)."""
        b = DynamicBatcher(_serving_model(fc_dir))
        outcomes = []

        def client():
            try:
                b.submit(_feed(), timeout=10)
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(e)

        threads = [threading.Thread(target=client) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        b.stop()
        for t in threads:
            t.join(timeout=5)
        assert len(outcomes) == 2
        assert all(isinstance(e, Unavailable) for e in outcomes), outcomes

    def test_continuous_stop_fails_queued_with_named_503(self, gen_model):
        from paddle_tpu.serving.generation import ContinuousBatcher

        b = ContinuousBatcher(gen_model)  # scheduler NOT started
        outcomes = []

        def client():
            try:
                b.submit([3, 5], max_tokens=2, timeout=10)
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(e)

        threads = [threading.Thread(target=client) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        t0 = time.perf_counter()
        b.stop()
        for t in threads:
            t.join(timeout=5)
        assert time.perf_counter() - t0 < 5.0
        assert len(outcomes) == 2
        assert all(isinstance(e, Unavailable) for e in outcomes), outcomes


# ---------------------------------------------------------------------------
# generation tier: bounded wait-queue + deadline expiry
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gen_model():
    from paddle_tpu.serving.generation import build_demo_generation_model

    model = build_demo_generation_model("gdemo", slots=2)
    model.warmup()  # pre-compile prefill+decode so tests time decode only
    return model


class TestGenerationRobustness:
    def test_gen_queue_depth_sheds(self, gen_model):
        from paddle_tpu.serving.generation import ContinuousBatcher

        FLAGS.monitor = True
        FLAGS.serving_max_queue_depth = 2
        b = ContinuousBatcher(gen_model)  # NOT started: queue only grows
        for _ in range(2):
            with pytest.raises(TimeoutError):
                b.submit([3, 5], max_tokens=2, timeout=0.01)
        with pytest.raises(Overloaded) as ei:
            b.submit([3, 5], max_tokens=2, timeout=0.01)
        assert ei.value.reason == "gen_queue_depth"
        assert default_registry().get(
            "serving.gen.gdemo.shed_total").value == 1
        b.stop()

    def test_gen_expired_queue_drop_never_admits(self, gen_model):
        """A request whose deadline passed while waiting for a slot is
        dropped pre-prefill — crafted directly because a submit() client
        marks its request cancelled on its own timeout (the cancel path;
        the deadline path must hold WITHOUT a live client thread)."""
        from paddle_tpu.serving.generation import (ContinuousBatcher,
                                                   _GenRequest)

        FLAGS.monitor = True
        b = ContinuousBatcher(gen_model)
        prefills = default_registry().counter(
            "serving.gen.gdemo.prefills").value
        expired = _GenRequest([3, 5], 4, timeout=0.05)
        b._queue.put(expired)
        time.sleep(0.06)
        b.start()
        # a live request flows; the expired one was dropped pre-prefill
        toks, meta = b.submit([4, 6], max_tokens=2, timeout=20)
        b.stop()
        assert len(toks) <= 2
        assert expired.event.is_set()
        assert isinstance(expired.error, TimeoutError)
        assert expired.tokens == []
        assert default_registry().get(
            "serving.gen.gdemo.expired_dropped_total").value == 1
        assert default_registry().get(
            "serving.gen.gdemo.prefills").value == prefills + 1

    def test_gen_breaker_opens_on_step_failures_and_recovers(
            self, gen_model):
        """The generation tier wires the same per-model breaker around
        its prefill/decode steps: a persistently broken generation model
        fails fast with 503 instead of burning a prefill per request."""
        from paddle_tpu.serving.generation import ContinuousBatcher

        FLAGS.monitor = True
        FLAGS.serving_breaker_threshold = 2
        FLAGS.serving_breaker_cooldown_s = 0.05
        b = ContinuousBatcher(gen_model)
        orig = gen_model.session.prefill

        def bad_prefill(*a, **kw):
            raise RuntimeError("prefill exploded")

        gen_model.session.prefill = bad_prefill
        try:
            b.start()
            for _ in range(2):
                with pytest.raises(RuntimeError, match="prefill exploded"):
                    b.submit([3, 5], max_tokens=2, timeout=10)
            assert b.breaker.state == CircuitBreaker.OPEN
            with pytest.raises(Unavailable) as ei:
                b.submit([3, 5], max_tokens=2, timeout=10)
            assert ei.value.reason == "breaker_open"
            assert default_registry().get(
                "serving.gen.gdemo.breaker_state").value \
                == CircuitBreaker.OPEN
            assert default_registry().get(
                "serving.gen.gdemo.breaker_rejected_total").value == 1
            # recovery: the half-open probe rides the fixed executor
            gen_model.session.prefill = orig
            time.sleep(0.06)
            toks, _ = b.submit([4, 6], max_tokens=2, timeout=20)
            assert len(toks) == 2
            assert b.breaker.state == CircuitBreaker.CLOSED
        finally:
            gen_model.session.prefill = orig
            b.stop()

    def test_gen_expired_slot_retires_at_step_boundary(self, gen_model):
        """Deadline expiry extends the PR-11 cancel path: the slot
        retires at the next iteration boundary even though the CLIENT
        thread never timed out (deadline is scheduler-side state)."""
        from paddle_tpu.serving.generation import (ContinuousBatcher,
                                                   _GenRequest)

        FLAGS.monitor = True
        b = ContinuousBatcher(gen_model)
        orig = gen_model.session.decode_step

        def slow_never_eos(tok, active=None):
            time.sleep(0.05)
            out = np.asarray(orig(tok, active=active))
            # pin non-eos so only max_tokens or the deadline can finish
            return np.where(out == gen_model.eos_id, 5, out)

        gen_model.session.decode_step = slow_never_eos
        try:
            b.start()
            req = _GenRequest([3, 5], 64, timeout=0.4)
            b._queue.put(req)
            assert req.event.wait(30), "expired slot never retired"
            assert isinstance(req.error, TimeoutError)
            assert 0 < len(req.tokens) < 64
            assert default_registry().get(
                "serving.gen.gdemo.expired_slots_total").value >= 1
            # the slot is reusable immediately
            toks, meta = b.submit([4, 6], max_tokens=2, timeout=20)
            assert len(toks) == 2
        finally:
            gen_model.session.decode_step = orig
            b.stop()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_state_machine(self):
        FLAGS.serving_breaker_threshold = 2
        FLAGS.serving_breaker_cooldown_s = 0.2
        cb = CircuitBreaker("m")
        assert cb.allow()
        cb.record_failure()
        assert cb.allow()  # under threshold
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        assert not cb.allow()  # open: fail fast
        time.sleep(0.25)
        assert cb.allow()  # cooldown over: ONE half-open probe
        assert cb.state == CircuitBreaker.HALF_OPEN
        assert not cb.allow()  # second caller rejected while probing
        cb.record_failure()  # probe failed -> re-open
        assert cb.state == CircuitBreaker.OPEN
        assert not cb.allow()
        time.sleep(0.25)
        assert cb.allow()
        cb.record_success()  # probe succeeded -> closed
        assert cb.state == CircuitBreaker.CLOSED
        assert cb.allow() and cb.allow()

    def test_lost_half_open_probe_reclaims(self):
        """A probe that never reaches the executor (shed, expired, or
        killed by a scheduler crash — nothing calls record_*) must not
        wedge the breaker half-open forever: the slot reclaims after a
        cooldown and the next caller becomes the probe."""
        FLAGS.serving_breaker_threshold = 1
        FLAGS.serving_breaker_cooldown_s = 0.1
        cb = CircuitBreaker("m")
        cb.record_failure()
        time.sleep(0.12)
        assert cb.allow()       # probe admitted... and then lost
        assert not cb.allow()   # slot held while the probe is live
        time.sleep(0.12)
        assert cb.allow()       # reclaimed: a new probe is admitted
        cb.record_success()
        assert cb.state == CircuitBreaker.CLOSED

    def test_shed_does_not_consume_probe_slot(self, fc_dir):
        """Queue-depth admission runs BEFORE the breaker: under the very
        overload that opened the breaker, sheds are 429s that leave the
        half-open probe slot for a request that can actually run."""
        FLAGS.serving_breaker_threshold = 1
        FLAGS.serving_breaker_cooldown_s = 0.05
        FLAGS.serving_max_queue_depth = 1
        b = DynamicBatcher(_serving_model(fc_dir))  # NOT started
        b.breaker.record_failure()  # open
        time.sleep(0.06)  # cooldown over: half-open on next allow()
        with pytest.raises(TimeoutError):  # the probe itself queues...
            b.submit(_feed(), timeout=0.01)
        # ...and the NEXT submit is a 429 shed, not a breaker 503 (the
        # breaker-first ordering would raise Unavailable here)
        with pytest.raises(Overloaded):
            b.submit(_feed(), timeout=0.01)
        time.sleep(0.06)
        assert b.breaker.allow()  # lost probe reclaimed despite sheds
        b.stop()

    def test_threshold_zero_disables(self):
        FLAGS.serving_breaker_threshold = 0
        cb = CircuitBreaker("m")
        for _ in range(10):
            cb.record_failure()
        assert cb.allow()
        assert cb.state == CircuitBreaker.CLOSED

    def test_breaker_opens_on_chaos_errors_and_recovers(self, fc_dir):
        """End to end on the chaos transient-error budget: consecutive
        executor failures open the breaker (fast 503, breaker_state
        gauge), the half-open probe rides the exhausted budget back to
        closed."""
        FLAGS.monitor = True
        FLAGS.serving_breaker_threshold = 2
        FLAGS.serving_breaker_cooldown_s = 0.05
        FLAGS.chaos = True
        FLAGS.chaos_serve_errors = 2
        chaos.reset()
        b = DynamicBatcher(_serving_model(fc_dir), max_batch=1)
        b.start()
        try:
            for _ in range(2):
                with pytest.raises(RuntimeError, match="chaos"):
                    b.submit(_feed(), timeout=10)
            assert b.breaker.state == CircuitBreaker.OPEN
            assert default_registry().get(
                "serving.m.breaker_state").value == CircuitBreaker.OPEN
            with pytest.raises(Unavailable) as ei:
                b.submit(_feed(), timeout=10)
            assert ei.value.reason == "breaker_open"
            assert default_registry().get(
                "serving.m.breaker_rejected_total").value == 1
            time.sleep(0.06)
            outs, _ = b.submit(_feed(), timeout=10)  # half-open probe
            assert outs is not None
            assert b.breaker.state == CircuitBreaker.CLOSED
            assert default_registry().get(
                "serving.m.breaker_state").value == CircuitBreaker.CLOSED
            assert chaos.injected_counts().get("serve_error") == 2
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# scheduler hardening + /health liveness
# ---------------------------------------------------------------------------


class TestSchedulerLiveness:
    def test_scheduler_exception_recovers_and_counts(self, fc_dir):
        FLAGS.monitor = True
        m = _serving_model(fc_dir)
        b = DynamicBatcher(m)
        b.start()
        try:
            boom = [True]

            def bad_pad(feed, rows, target):
                if boom:
                    boom.pop()
                    raise RuntimeError("pad exploded")
                return ServingModel.pad_feed(feed, rows, target)

            m.pad_feed = bad_pad
            with pytest.raises(RuntimeError, match="pad exploded"):
                b.submit(_feed(), timeout=10)
            # the loop survived: the next request is served normally
            outs, _ = b.submit(_feed(), timeout=10)
            assert outs is not None
            assert b.scheduler_alive
            assert default_registry().get(
                "serving.m.scheduler_restarts").value == 1
            evs = flight.default_recorder().events(
                kind="serving.scheduler_error")
            assert evs and evs[-1]["fatal"]
        finally:
            b.stop()

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_dead_scheduler_flips_health_503(self, fc_dir):
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        try:
            body, code = mserve.health_body()
            assert code == 200 and body["status"] == "ok"
            b = srv._batchers["m"]

            def die(*a, **kw):
                raise SystemExit("scheduler killed")  # BaseException class

            b._take = die
            b._thread.join(timeout=10)
            assert not b._thread.is_alive()
            assert not b.scheduler_alive
            body, code = mserve.health_body()
            assert code == 503
            assert body["status"] == "scheduler_dead"
            assert body["serving"]["scheduler_dead"] == ["m"]
            evs = flight.default_recorder().events(
                kind="serving.scheduler_dead")
            assert evs and evs[-1]["model"] == "m"
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# graceful drain (in-process)
# ---------------------------------------------------------------------------


class TestGracefulDrain:
    def test_drain_completes_admitted_and_503s_new(self, fc_dir):
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        stopped = False
        try:
            m = srv._models["m"]
            orig = m.run_batch

            def slow(*a, **kw):
                time.sleep(0.4)
                return orig(*a, **kw)

            m.run_batch = slow
            res = {}

            def client():
                try:
                    res["out"] = srv.submit("m", _feed(), timeout=10)
                except Exception as e:  # noqa: BLE001
                    res["err"] = e

            t = threading.Thread(target=client)
            t.start()
            time.sleep(0.1)
            dr = {}
            td = threading.Thread(
                target=lambda: dr.setdefault(
                    "ok", srv.drain(timeout_s=10)))
            td.start()
            time.sleep(0.1)
            # mid-drain: /health says draining (503), new work is 503
            body, code = mserve.health_body()
            assert code == 503 and body["status"] == "draining"
            assert body["serving"]["draining"] is True
            with pytest.raises(Unavailable) as ei:
                srv.submit("m", _feed(), timeout=1)
            assert ei.value.reason == "draining"
            td.join(timeout=20)
            t.join(timeout=20)
            stopped = True  # drain() ends in stop()
            assert dr.get("ok") is True
            assert "out" in res, res
            evs = flight.default_recorder().events(kind="serving.drain")
            assert evs
        finally:
            if not stopped:
                srv.stop()

    def test_drain_timeout_bounds_the_wait(self, fc_dir):
        """A drain with stuck work returns (False) inside its budget
        instead of hanging."""
        srv = InferenceServer(
            [ModelConfig("m", fc_dir, buckets="1,2", max_wait_ms=1.0)],
            port=0)
        srv.start(warmup=True)
        try:
            m = srv._models["m"]
            orig = m.run_batch

            def stuck(*a, **kw):
                time.sleep(3.0)
                return orig(*a, **kw)

            m.run_batch = stuck
            t = threading.Thread(
                target=lambda: _swallow(
                    lambda: srv.submit("m", _feed(), timeout=10)))
            t.start()
            time.sleep(0.1)
            t0 = time.monotonic()
            ok = srv.drain(timeout_s=0.5)
            assert time.monotonic() - t0 < 2.5
            assert ok is False
            t.join(timeout=10)
        finally:
            srv.stop()


def _swallow(fn):
    try:
        fn()
    except Exception:  # noqa: BLE001 — outcome irrelevant to the test
        pass


# ---------------------------------------------------------------------------
# subprocess SIGTERM graceful drain (satellite)
# ---------------------------------------------------------------------------


def _http_get(url, data=None, timeout=5):
    """-> (status, body bytes); HTTP errors return their status+body."""
    headers = {"Content-Type": "application/json"} if data else {}
    req = urllib.request.Request(url, data=data, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestSigtermDrainSubprocess:
    def test_sigterm_drains_inflight_and_exits_zero(self, tmp_path):
        """The full CLI contract: an in-flight request completes 200
        through the drain, a request sent DURING the drain gets 503,
        the flight dump names trigger 'drain', and the process exits 0
        within the drain timeout."""
        model_dir = _export_fc_model(str(tmp_path / "fc32"), in_dim=4)
        flight_dir = str(tmp_path / "flight")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   FLAGS_chaos="1",
                   FLAGS_chaos_serve_latency_s="0.5",
                   FLAGS_serving_drain_timeout_s="10",
                   FLAGS_flight_dir=flight_dir)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving",
             "--port", "0", "--model", f"demo={model_dir}",
             "--buckets", "1,2", "--max-wait-ms", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=REPO_ROOT, env=env, text=True)
        try:
            line = proc.stdout.readline()
            ready = json.loads(line)
            url = f"http://127.0.0.1:{ready['port']}"
            results = []

            def inflight():
                req = urllib.request.Request(
                    f"{url}/v1/models/demo:predict",
                    data=json.dumps({"inputs": {"x": [[0.1] * 4]},
                                     "timeout_s": 20}).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        results.append((r.status, r.read()))
                except urllib.error.HTTPError as e:
                    results.append((e.code, e.read()))

            t = threading.Thread(target=inflight)
            t.start()
            # wait until the request is ADMITTED (inflight gauge via
            # /metrics), then SIGTERM mid-execution
            deadline = time.time() + 10
            while time.time() < deadline:
                with urllib.request.urlopen(f"{url}/metrics",
                                            timeout=5) as r:
                    text = r.read().decode()
                if any(ln.startswith("serving_demo_inflight 1")
                       for ln in text.splitlines()):
                    break
                time.sleep(0.02)
            t_term = time.monotonic()
            proc.send_signal(signal.SIGTERM)
            time.sleep(0.15)
            # during the drain: health says draining, new request 503
            code, raw = _http_get(f"{url}/health")
            assert code == 503, (code, raw)
            assert json.loads(raw)["status"] == "draining"
            code, raw = _http_get(
                f"{url}/v1/models/demo:predict",
                data=json.dumps({"inputs": {"x": [[0.1] * 4]}}).encode())
            assert code == 503, (code, raw)
            assert json.loads(raw)["reason"] == "draining"
            # the admitted in-flight request completes 200
            t.join(timeout=30)
            assert results and results[0][0] == 200, results
            # process exits 0 inside the drain budget
            rc = proc.wait(timeout=20)
            assert rc == 0, rc
            assert time.monotonic() - t_term < 15
            # the flight dump names the drain trigger
            dumps = glob.glob(
                os.path.join(flight_dir, "flight-*-drain.jsonl"))
            assert dumps, os.listdir(flight_dir)
            with open(dumps[0]) as f:
                header = json.loads(f.readline())
            assert header["trigger"] == "drain"
            kinds = [json.loads(ln).get("kind")
                     for ln in open(dumps[0]).read().splitlines()[1:]]
            assert "serving.drain" in kinds
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# zero-cost-when-off (the PR-1/PR-3 convention)
# ---------------------------------------------------------------------------


class TestZeroCostOff:
    def test_chaos_hooks_noop_when_off(self):
        assert not FLAGS.chaos
        t0 = time.perf_counter()
        for _ in range(100):
            chaos.maybe_serve_latency()
            chaos.maybe_serve_error("site")
            assert chaos.serve_flood() == 0
        assert time.perf_counter() - t0 < 0.5
        assert chaos.injected_counts() == {}

    def test_monitor_off_registers_no_robustness_metrics(self, fc_dir):
        assert not FLAGS.monitor
        FLAGS.serving_max_queue_depth = 1
        FLAGS.serving_breaker_threshold = 1
        m = _serving_model(fc_dir)
        b = DynamicBatcher(m)
        with pytest.raises(TimeoutError):
            b.submit(_feed(), timeout=0.01)
        with pytest.raises(Overloaded):  # shed path, no counters
            b.submit(_feed(), timeout=0.01)
        time.sleep(0.02)
        b.start()  # expired-drop path, no counters
        b.breaker.record_failure()  # breaker open, no gauge
        with pytest.raises(Unavailable):
            b.submit(_feed(), timeout=0.01)
        b.stop()
        reg = default_registry()
        for name in ("serving.m.shed_total", "serving.shed_total",
                     "serving.m.expired_dropped_total",
                     "serving.expired_dropped_total",
                     "serving.m.breaker_state",
                     "serving.m.breaker_rejected_total",
                     "serving.m.scheduler_restarts"):
            assert reg.get(name) is None, name
        assert not flight.default_recorder().events()

    def test_flags_off_restores_legacy_admission(self, fc_dir):
        """Queue depth 0 + breaker 0 + inflight 0 = today's semantics:
        every validated request is admitted, breaker never consulted."""
        FLAGS.serving_max_queue_depth = 0
        FLAGS.serving_breaker_threshold = 0
        FLAGS.serving_max_inflight = 0
        m = _serving_model(fc_dir)
        b = DynamicBatcher(m)
        for _ in range(5):
            b.breaker.record_failure()  # ignored while disabled
        b.start()
        outcomes = []

        def client():
            try:
                outs, _ = b.submit(_feed(), timeout=10)
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(e)

        threads = [threading.Thread(target=client) for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        b.stop()
        assert outcomes == ["ok"] * 12, outcomes
