"""CTC family: warpctc loss numerics vs torch's CPU CTC, gradient check,
ctc_align / ctc_greedy_decoder vs brute force, and an OCR-style integration
test (conv + GRU + CTC trained on synthetic strings; greedy decode recovers
the planted string). Reference: operators/warpctc_op.cc, ctc_align_op.cc,
layers/nn.py:4783 (ctc_greedy_decoder), :4866 (warpctc)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

from op_test import OpTest

rng = np.random.RandomState(7)


def _torch_ctc(logits, labels, llens, tlens, blank):
    torch = pytest.importorskip("torch")
    lg = torch.tensor(logits, dtype=torch.float64, requires_grad=True)
    logp = torch.nn.functional.log_softmax(lg, dim=-1)
    # torch wants [T, B, C]
    loss = torch.nn.functional.ctc_loss(
        logp.transpose(0, 1),
        torch.tensor(labels, dtype=torch.long),
        torch.tensor(llens, dtype=torch.long),
        torch.tensor(tlens, dtype=torch.long),
        blank=blank,
        reduction="none",
        zero_infinity=False,
    )
    loss.sum().backward()
    return loss.detach().numpy(), lg.grad.numpy()


class TestWarpCTC(OpTest):
    op_type = "warpctc"

    def test_loss_matches_torch(self):
        B, T, C, L = 4, 12, 6, 5
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int32")
        llens = np.array([12, 9, 7, 12], "int32")
        tlens = np.array([5, 3, 2, 4], "int32")
        expected, _ = _torch_ctc(logits, labels, llens, tlens, blank=0)
        self.check_output(
            inputs={
                "Logits": [("lg", logits)],
                "Label": [("lb", labels)],
                "Logits_length": [("ll", llens)],
                "Label_length": [("tl", tlens)],
            },
            outputs={"Loss": [("loss", expected.reshape(B, 1))]},
            attrs={"blank": 0, "norm_by_times": False},
            atol=1e-4, rtol=1e-4,
        )

    def test_nonzero_blank_and_full_lengths(self):
        B, T, C, L = 3, 8, 5, 3
        blank = C - 1
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(0, C - 1, (B, L)).astype("int32")
        llens = np.full((B,), T, "int32")
        tlens = np.full((B,), L, "int32")
        expected, _ = _torch_ctc(logits, labels, llens, tlens, blank=blank)
        self.check_output(
            inputs={"Logits": [("lg", logits)], "Label": [("lb", labels)]},
            outputs={"Loss": [("loss", expected.reshape(B, 1))]},
            attrs={"blank": blank, "norm_by_times": False},
            atol=1e-4, rtol=1e-4,
        )

    def test_grad_matches_torch(self):
        """Analytic vjp gradient wrt raw logits vs torch autograd."""
        B, T, C, L = 3, 10, 5, 4
        logits = rng.randn(B, T, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int32")
        llens = np.array([10, 8, 6], "int32")
        tlens = np.array([4, 2, 3], "int32")
        _, expected_grad = _torch_ctc(logits, labels, llens, tlens, blank=0)

        from paddle_tpu.core import framework as fw
        prog = fw.Program()
        startup = fw.Program()
        with fw.program_guard(prog, startup):
            lg = layers.data(name="lg", shape=[T, C], dtype="float32")
            lg.stop_gradient = False
            lb = layers.data(name="lb", shape=[L], dtype="int32")
            ll = layers.data(name="ll", shape=[], dtype="int32")
            tl = layers.data(name="tl", shape=[], dtype="int32")
            loss = layers.warpctc(lg, lb, blank=0, input_length=ll,
                                  label_length=tl)
            total = layers.reduce_sum(loss)
            grads = pt.calc_gradient(total, [lg])
        exe = pt.Executor(pt.CPUPlace())
        (g,) = exe.run(
            prog,
            feed={"lg": logits, "lb": labels, "ll": llens, "tl": tlens},
            fetch_list=[grads[0]],
        )
        np.testing.assert_allclose(np.asarray(g), expected_grad,
                                   atol=2e-4, rtol=1e-3)


def _align_ref(tokens, lens, blank):
    out = []
    for row, ln in zip(tokens, lens):
        cur, prev = [], None
        for tok in row[:ln]:
            if tok != blank and tok != prev:
                cur.append(int(tok))
            prev = tok
        out.append(cur)
    return out


class TestCtcAlign(OpTest):
    op_type = "ctc_align"

    def test_align(self):
        B, T = 5, 9
        x = rng.randint(0, 4, (B, T)).astype("int32")
        lens = np.array([9, 7, 4, 9, 1], "int32")
        ref = _align_ref(x, lens, blank=0)
        expected = np.zeros((B, T), "int32")
        for i, r in enumerate(ref):
            expected[i, : len(r)] = r
        got = self.check_output(
            inputs={"Input": [("x", x)], "Length": [("l", lens)]},
            outputs={"Output": [("o", expected)],
                     "OutLength": [("ol", np.array([len(r) for r in ref],
                                                   "int32"))]},
            attrs={"blank": 0, "padding_value": 0},
        )
        assert got is not None


def test_ctc_greedy_decoder_layer():
    B, T, C = 3, 6, 4
    probs = rng.rand(B, T, C).astype("float32")
    inp = layers.data(name="p", shape=[T, C], dtype="float32")
    dec, dec_len = layers.ctc_greedy_decoder(inp, blank=0)
    exe = pt.Executor(pt.CPUPlace())
    o, ol = exe.run(feed={"p": probs}, fetch_list=[dec, dec_len])
    tokens = probs.argmax(-1)
    ref = _align_ref(tokens, [T] * B, blank=0)
    for i, r in enumerate(ref):
        assert list(np.asarray(o)[i, : len(r)]) == r
        assert int(np.asarray(ol)[i]) == len(r)


def test_ocr_ctc_trains_and_decodes():
    """conv + GRU + CTC on synthetic 'images' whose columns encode a token
    string; loss decreases and greedy decode recovers the planted string."""
    B, T, H, C = 8, 12, 8, 5  # C classes incl. blank 0
    rs = np.random.RandomState(3)
    # each class c gets a distinctive column pattern
    patterns = rs.randn(C, H).astype("float32") * 2.0

    def make_batch():
        lab = rs.randint(1, C, (B, 4)).astype("int32")
        img = np.zeros((B, 1, H, T), "float32")
        for i in range(B):
            # paint each token over 3 columns
            for j, c in enumerate(lab[i]):
                img[i, 0, :, 3 * j : 3 * j + 3] = patterns[c][:, None]
        img += rs.randn(*img.shape).astype("float32") * 0.1
        return img, lab

    img = layers.data(name="img", shape=[1, H, T], dtype="float32")
    lab = layers.data(name="lab", shape=[4], dtype="int32")
    conv = layers.conv2d(img, num_filters=16, filter_size=3, padding=1,
                         act="relu")                       # [B,16,H,T]
    feat = layers.transpose(conv, [0, 3, 1, 2])            # [B,T,16,H]
    feat = layers.reshape(feat, [-1, T, 16 * H])
    gru = layers.dynamic_gru(layers.fc(feat, size=3 * 32, num_flatten_dims=2),
                             size=32)
    logits = layers.fc(gru, size=C, num_flatten_dims=2)    # [B,T,C]
    loss = layers.warpctc(logits, lab, blank=0)
    avg = layers.mean(loss)
    dec, dec_len = layers.ctc_greedy_decoder(logits, blank=0)
    pt.optimizer.AdamOptimizer(learning_rate=0.01).minimize(avg)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(120):
        x, y = make_batch()
        (lv,) = exe.run(feed={"img": x, "lab": y}, fetch_list=[avg])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    test_prog = pt.default_main_program().clone(for_test=True)
    x, y = make_batch()
    o, ol = exe.run(test_prog, feed={"img": x, "lab": y},
                    fetch_list=[dec, dec_len])
    o, ol = np.asarray(o), np.asarray(ol)
    hits = sum(
        1 for i in range(B)
        if ol[i] == 4 and list(o[i, :4]) == list(y[i])
    )
    assert hits >= B - 2, (hits, o[:, :6], y)
