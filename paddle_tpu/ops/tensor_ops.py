"""Tensor manipulation ops (reference: operators/ reshape_op.cc,
transpose_op.cc, concat_op.cc, split_op.cc, slice_op.cc, cast_op.cc,
fill_constant_op.cc, one_hot_op.cc, gather_op.cc, scatter_op.cc,
expand_op.cc, top_k_op.cc, arg_min_max_op_base.h, cum_op.h, pad_op.cc, ...).

All static-shape by construction — attrs carry the shape parameters, so XLA
sees fully static programs (no dynamic shapes that would block MXU tiling).
"""

from __future__ import annotations

import numpy as np

from ..core.framework import convert_dtype
from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _resolve_shape(shape, x):
    """Resolve -1 / 0 entries in a reshape target (reference reshape_op.cc:
    0 copies the input dim, -1 is inferred)."""
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(x.shape))
        shape[shape.index(-1)] = total // max(known, 1)
    return tuple(int(s) for s in shape)


def _reshape_infer(ctx):
    xs = ctx.input_shape("X")
    shape = ctx.attr("shape")
    if xs is None or shape is None:
        return
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = xs[i]
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(xs))
        shape[shape.index(-1)] = total // max(known, 1)
    ctx.set_output("Out", shape, ctx.input_dtype("X"))


@register("reshape", infer_shape=_reshape_infer)
def lower_reshape(ctx, ins):
    x = ins["X"][0]
    return {"Out": [x.reshape(_resolve_shape(ctx.attr("shape"), x))]}


@register("reshape2", infer_shape=_reshape_infer)
def lower_reshape2(ctx, ins):
    x = ins["X"][0]
    out = x.reshape(_resolve_shape(ctx.attr("shape"), x))
    jnp = _jnp()
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


def _transpose_infer(ctx):
    xs = ctx.input_shape("X")
    axis = ctx.attr("axis")
    if xs is None or axis is None:
        return
    ctx.set_output("Out", [xs[a] for a in axis], ctx.input_dtype("X"))


@register("transpose", infer_shape=_transpose_infer)
def lower_transpose(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.transpose(ins["X"][0], ctx.attr("axis"))]}


@register("transpose2", infer_shape=_transpose_infer)
def lower_transpose2(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    out = jnp.transpose(x, ctx.attr("axis"))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


def _concat_infer(ctx):
    n = len(ctx.op.input("X"))
    shapes = [ctx.input_shape("X", i) for i in range(n)]
    if not shapes or any(s is None for s in shapes):
        return  # unknown input: leave output shape unset, not wrong
    axis = ctx.attr("axis", 0)
    out = list(shapes[0])
    out[axis] = sum(s[axis] for s in shapes)
    ctx.set_output("Out", out, ctx.input_dtype("X"))


@register("concat", infer_shape=_concat_infer)
def lower_concat(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.concatenate([v for v in ins["X"]], axis=ctx.attr("axis", 0))]}


@register("split")
def lower_split(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", 0)
    sections = ctx.attr("sections")
    num = ctx.attr("num", 0)
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("slice")
def lower_slice(ctx, ins):
    x = ins["Input"][0]
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


def _squeeze_axes(shape, axes):
    if axes:
        return [i for i in range(len(shape)) if not (i in axes or i - len(shape) in axes)]
    return [i for i, s in enumerate(shape) if s != 1]


@register("squeeze")
def lower_squeeze(ctx, ins):
    x = ins["X"][0]
    keep = _squeeze_axes(x.shape, ctx.attr("axes", []))
    return {"Out": [x.reshape(tuple(x.shape[i] for i in keep))]}


@register("squeeze2")
def lower_squeeze2(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    keep = _squeeze_axes(x.shape, ctx.attr("axes", []))
    out = x.reshape(tuple(x.shape[i] for i in keep))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


def _unsqueeze_shape(shape, axes):
    out = list(shape)
    for ax in sorted(a if a >= 0 else a + len(shape) + 1 for a in axes):
        out.insert(ax, 1)
    return tuple(out)


@register("unsqueeze")
def lower_unsqueeze(ctx, ins):
    x = ins["X"][0]
    return {"Out": [x.reshape(_unsqueeze_shape(x.shape, ctx.attr("axes")))]}


@register("unsqueeze2")
def lower_unsqueeze2(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    out = x.reshape(_unsqueeze_shape(x.shape, ctx.attr("axes")))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


def _flatten_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is None:
        return
    axis = ctx.attr("axis", 1)
    outer = int(np.prod(xs[:axis])) if axis > 0 else 1
    inner = int(np.prod(xs[axis:])) if axis < len(xs) else 1
    ctx.set_output("Out", (outer, inner), ctx.input_dtype("X"))


@register("flatten", infer_shape=_flatten_infer)
def lower_flatten(ctx, ins):
    x = ins["X"][0]
    axis = ctx.attr("axis", 1)
    outer = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape((outer, -1))]}


@register("flatten2", infer_shape=_flatten_infer)
def lower_flatten2(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", 1)
    outer = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    out = x.reshape((outer, -1))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


def _stack_infer(ctx):
    s = ctx.input_shape("X", 0)
    if s is None:
        return
    n = len(ctx.op.input("X"))
    axis = ctx.attr("axis", 0)
    if axis < 0:
        axis += len(s) + 1
    out = list(s)
    out.insert(axis, n)
    ctx.set_output("Y", out, ctx.input_dtype("X"))


@register("stack", infer_shape=_stack_infer)
def lower_stack(ctx, ins):
    jnp = _jnp()
    return {"Y": [jnp.stack([v for v in ins["X"]], axis=ctx.attr("axis", 0))]}


@register("unstack")
def lower_unstack(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", 0)
    n = x.shape[axis]
    outs = [jnp.squeeze(v, axis=axis) for v in jnp.split(x, n, axis=axis)]
    return {"Y": outs}


def _cast_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is not None:
        ctx.set_output("Out", xs, ctx.attr("out_dtype", "float32"))


def _canon_i64():
    """int64 clamped through jax's canonical-dtype helper: index outputs
    (argmax/top_k/...) keep reference int64 semantics under x64 but become
    int32 EXPLICITLY when x64 is off, instead of truncate-and-warn on
    every trace."""
    import jax

    return jax.dtypes.canonicalize_dtype(np.int64)


def _requested_dtype(attr_value):
    """Program dtype attr -> the dtype JAX will actually produce: bfloat16
    stays symbolic, everything else is clamped through jax's canonical-
    dtype helper so an int64/float64 request with x64 disabled becomes
    int32/float32 EXPLICITLY instead of letting jnp truncate-and-warn on
    every trace (the bench-visible UserWarning at fill_constant sites)."""
    import jax

    jnp = _jnp()
    dtype = convert_dtype(attr_value)
    if dtype == "bfloat16":
        return jnp.bfloat16
    return jax.dtypes.canonicalize_dtype(np.dtype(dtype))


@register("cast", infer_shape=_cast_infer)
def lower_cast(ctx, ins):
    target = _requested_dtype(ctx.attr("out_dtype", "float32"))
    return {"Out": [ins["X"][0].astype(target)]}


def _fill_constant_infer(ctx):
    ctx.set_output("Out", ctx.attr("shape", [1]), ctx.attr("dtype", "float32"))


@register("fill_constant", infer_shape=_fill_constant_infer, no_grad=True)
def lower_fill_constant(ctx, ins):
    jnp = _jnp()
    target = _requested_dtype(ctx.attr("dtype", "float32"))
    shape = tuple(int(s) for s in ctx.attr("shape", [1]))
    return {"Out": [jnp.full(shape, ctx.attr("value", 0.0), dtype=target)]}


@register("fill_zeros_like", no_grad=True)
def lower_fill_zeros_like(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register("assign")
def lower_assign(ctx, ins):
    return {"Out": [ins["X"][0]]}


@register("assign_value", no_grad=True)
def lower_assign_value(ctx, ins):
    jnp = _jnp()
    values = np.array(ctx.attr("values"), dtype=convert_dtype(ctx.attr("dtype", "float32")))
    return {"Out": [jnp.asarray(values.reshape(ctx.attr("shape")))]}


@register("shape", no_grad=True)
def lower_shape(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.asarray(np.array(ins["Input"][0].shape, dtype=np.int32))]}


@register("one_hot", no_grad=True)
def lower_one_hot(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    depth = ctx.attr("depth")
    x = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


@register("arg_max", no_grad=True)
def lower_arg_max(ctx, ins):
    jnp = _jnp()
    return {
        "Out": [jnp.argmax(ins["X"][0], axis=ctx.attr("axis", -1)).astype(_canon_i64())]
    }


@register("arg_min", no_grad=True)
def lower_arg_min(ctx, ins):
    jnp = _jnp()
    return {
        "Out": [jnp.argmin(ins["X"][0], axis=ctx.attr("axis", -1)).astype(_canon_i64())]
    }


@register("argsort", no_grad=True)
def lower_argsort(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": [jnp.take_along_axis(x, idx, axis=axis)], "Indices": [idx.astype(_canon_i64())]}


def _top_k_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is not None:
        k = ctx.attr("k", 1)
        out = tuple(xs[:-1]) + (int(k),)
        ctx.set_output("Out", out, ctx.input_dtype("X"))
        ctx.set_output("Indices", out)


@register("top_k", no_grad=True, infer_shape=_top_k_infer)
def lower_top_k(ctx, ins):
    import jax

    x = ins["X"][0]
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(_canon_i64())]}


@register("cumsum")
def lower_cumsum(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    axis = ctx.attr("axis", -1)
    if ctx.attr("flatten", False):
        x = x.reshape(-1)
        axis = 0
    if ctx.attr("reverse", False):
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if ctx.attr("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        out = jnp.pad(out, pad)[tuple(sl)]
    if ctx.attr("reverse", False):
        out = jnp.flip(out, axis=axis)
    return {"Out": [out]}


@register("gather")
def lower_gather(ctx, ins):
    jnp = _jnp()
    x, idx = ins["X"][0], ins["Index"][0]
    idx = idx.reshape(-1)
    return {"Out": [jnp.take(x, idx, axis=0)]}


@register("scatter")
def lower_scatter(ctx, ins):
    x, idx, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    idx = idx.reshape(-1)
    if ctx.attr("overwrite", True):
        out = x.at[idx].set(updates)
    else:
        out = x.at[idx].add(updates)
    return {"Out": [out]}


def _expand_infer(ctx):
    xs = ctx.input_shape("X")
    times = ctx.attr("expand_times")
    if xs is None or times is None:
        return
    ctx.set_output(
        "Out",
        tuple(int(s) * int(t) for s, t in zip(xs, times)),
        ctx.input_dtype("X"),
    )


@register("expand", infer_shape=_expand_infer)
def lower_expand(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    times = ctx.attr("expand_times")
    return {"Out": [jnp.tile(x, times)]}


@register("expand_as")
def lower_expand_as(ctx, ins):
    jnp = _jnp()
    x, target = ins["X"][0], ins["target_tensor"][0]
    times = [t // s for s, t in zip(x.shape, target.shape)]
    return {"Out": [jnp.tile(x, times)]}


@register("pad")
def lower_pad(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    paddings = ctx.attr("paddings")
    pad_width = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return {
        "Out": [
            jnp.pad(x, pad_width, constant_values=ctx.attr("pad_value", 0.0))
        ]
    }


@register("pad2d")
def lower_pad2d(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    p = ctx.attr("paddings", [0, 0, 0, 0])
    mode = ctx.attr("mode", "constant")
    fmt = ctx.attr("data_format", "NCHW")
    if fmt == "NCHW":
        pad_width = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pad_width = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        out = jnp.pad(x, pad_width, constant_values=ctx.attr("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pad_width, mode="reflect")
    else:
        out = jnp.pad(x, pad_width, mode="edge")
    return {"Out": [out]}


@register("pad_constant_like")
def lower_pad_constant_like(ctx, ins):
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    pad_width = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {
        "Out": [jnp.pad(y, pad_width, constant_values=ctx.attr("pad_value", 0.0))]
    }


@register("mean")
def lower_mean(ctx, ins):
    jnp = _jnp()
    return {"Out": [jnp.mean(ins["X"][0])]}


@register("reverse")
def lower_reverse(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    out = x
    for ax in ctx.attr("axis"):
        out = jnp.flip(out, axis=ax)
    return {"Out": [out]}


@register("space_to_depth")
def lower_space_to_depth(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0]
    bs = ctx.attr("blocksize")
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // bs, bs, w // bs, bs)
    out = jnp.transpose(out, (0, 3, 5, 1, 2, 4))
    return {"Out": [out.reshape(n, c * bs * bs, h // bs, w // bs)]}


@register("increment")
def lower_increment(ctx, ins):
    x = ins["X"][0]
    # keep the var's dtype: int counters must not promote to float
    step = _jnp().asarray(ctx.attr("step", 1.0), dtype=x.dtype)
    return {"Out": [x + step]}


@register("isfinite", no_grad=True)
def lower_isfinite(ctx, ins):
    jnp = _jnp()
    vals = [v for v in ins["X"] if v is not None]
    ok = jnp.array(True)
    for v in vals:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(v.astype(jnp.float32))))
    return {"Out": [ok]}


def _take_along_axis_infer(ctx):
    idx = ctx.input_shape("Index")
    if idx is None:
        return
    ctx.set_output("Out", tuple(idx), ctx.input_dtype("X"))


@register("take_along_axis", infer_shape=_take_along_axis_infer)
def lower_take_along_axis(ctx, ins):
    """Batched gather: out[..., i, ...] = x[..., idx[..., i, ...], ...]
    along `axis` (numpy take_along_axis semantics).  The reference's closest
    op is gather (gather_op.cc) which only indexes dim 0; beam-search
    hypothesis reordering needs the batched form, and XLA lowers it to one
    fused gather (grad = scatter-add via the default vjp maker)."""
    jnp = _jnp()
    x, idx = ins["X"][0], ins["Index"][0]
    axis = ctx.attr("axis", 0)
    return {"Out": [jnp.take_along_axis(x, idx, axis=axis)]}
