"""QAT fake-quantization ops (reference: operators/fake_quantize_op.cc:1,
fake_dequantize_op.cc).

TPU-first: the straight-through estimator is baked into the lowering as
`base + stop_gradient(quantize(base) - base)`, so the generic vjp grad maker
yields the reference's pass-through gradient with no explicit grad ops, and
the round/clip chain fuses into the surrounding XLA computation.  The
moving-average scale follows the batch_norm stateful contract: OutScale /
state outputs reuse the input var names and the executor writes them back
to the Scope.
"""

from __future__ import annotations

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _ste(base, quantized):
    """Forward `quantized`, gradient of `base` (straight-through)."""
    import jax

    return base + jax.lax.stop_gradient(quantized - base)


def _qrange(ctx):
    bits = ctx.attr("bit_length", 8)
    return float((1 << (bits - 1)) - 1)


@register("fake_quantize_abs_max")
def lower_fake_quantize_abs_max(ctx, ins):
    """Out = clip(round(X / max|X| * range)); OutScale = max|X|
    (reference fake_quantize_op.cc FakeQuantizeAbsMaxOp)."""
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    r = _qrange(ctx)
    # scale is data, not a differentiable function of x (the reference's
    # grad is pure pass-through)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)).astype(jnp.float32))
    safe = jnp.maximum(scale, 1e-8)
    base = x.astype(jnp.float32) / safe * r
    q = jnp.clip(jnp.round(base), -r, r)
    return {
        "Out": [_ste(base, q).astype(x.dtype)],
        "OutScale": [scale.reshape(1)],
    }


@register("fake_quantize_moving_average_abs_max")
def lower_fake_quantize_moving_average_abs_max(ctx, ins):
    """Activation quantization with a moving-average abs-max scale
    (reference fake_quantize_op.cc FakeQuantizeMovingAverageAbsMaxOp).
    State (InAccum/InState/InScale) is read and written back by name."""
    jnp = _jnp()
    x = ins["X"][0]
    r = _qrange(ctx)
    rho = ctx.attr("moving_rate", 0.9)
    is_test = ctx.attr("is_test", False) or ctx.is_test

    in_scale = ins["InScale"][0].reshape(())
    if is_test:
        scale = in_scale
        accum_out = ins["InAccum"][0] if ins.get("InAccum") else None
        state_out = ins["InState"][0] if ins.get("InState") else None
    else:
        cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
        accum = ins["InAccum"][0].reshape(()) * rho + cur
        state = ins["InState"][0].reshape(()) * rho + 1.0
        scale = accum / state
        accum_out = accum.reshape(1)
        state_out = state.reshape(1)

    import jax

    scale = jax.lax.stop_gradient(scale)
    safe = jnp.maximum(scale, 1e-8)
    base = x.astype(jnp.float32) / safe * r
    q = jnp.clip(jnp.round(base), -r, r)
    outs = {
        "Out": [_ste(base, q).astype(x.dtype)],
        "OutScale": [scale.reshape(1)],
    }
    if accum_out is not None:
        outs["OutAccum"] = [accum_out]
    if state_out is not None:
        outs["OutState"] = [state_out]
    return outs


@register("fake_dequantize_max_abs")
def lower_fake_dequantize_max_abs(ctx, ins):
    """Out = X * Scale / max_range (reference fake_dequantize_op.cc)."""
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    scale = jax.lax.stop_gradient(ins["Scale"][0].reshape(()))
    max_range = ctx.attr("max_range", _qrange(ctx))
    return {"Out": [(x.astype(jnp.float32) * scale / max_range
                     ).astype(x.dtype)]}


# -- int8 inference execution (reference quantize_op.cc / dequantize_op.cc,
#    the mkldnn int8 path; TPU-first: int8 storage + int32-accumulated
#    dot_general, scales folded back in fp32) -------------------------------


@register("quantize", no_grad=True)
def lower_quantize(ctx, ins):
    """f32 -> int8 with a scale (Scale input [1] or attr): q = clip(
    round(x / scale * 127), -127, 127)."""
    jnp = _jnp()
    x = ins["X"][0]
    if ins.get("Scale"):
        scale = ins["Scale"][0].reshape(())
    else:
        scale = jnp.asarray(ctx.attr("scale", 1.0), x.dtype)
    q = jnp.clip(jnp.round(x / scale * 127.0), -127, 127)
    return {"Out": [q.astype(jnp.int8)]}


@register("dequantize", no_grad=True)
def lower_dequantize(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0].astype(jnp.float32)
    if ins.get("Scale"):
        scale = ins["Scale"][0].reshape(())
    else:
        scale = ctx.attr("scale", 1.0)
    return {"Out": [x * scale / 127.0]}


@register("int8_mul", no_grad=True)
def lower_int8_mul(ctx, ins):
    """int8 x int8 matmul with int32 accumulation; output rescaled to f32
    by sx*sy/127^2.  The executable int8 path the reference reaches via
    its mkldnn quantize/dequantize kernels."""
    import jax.lax as lax

    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    sx = ins["ScaleX"][0].reshape(()) if ins.get("ScaleX") else 1.0
    sy = ins["ScaleY"][0].reshape(()) if ins.get("ScaleY") else 1.0
    # honor the mul op's flatten attrs (freeze_int8 keeps them): X flattens
    # to [prod(dims[:nx]), prod(dims[nx:])] and Y to
    # [prod(dims[:ny]), prod(dims[ny:])] like lower_mul
    nx = ctx.attr("x_num_col_dims", 1)
    ny = ctx.attr("y_num_col_dims", 1)
    lead = x.shape[:nx]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, -1)
    if y.ndim > 2 or ny != 1:
        k = 1
        for d in y.shape[:ny]:
            k *= d
        y = y.reshape(k, -1)
    acc = lax.dot_general(
        x2, y, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (sx * sy / (127.0 * 127.0))
    return {"Out": [out.reshape(tuple(lead) + (y.shape[1],))]}


@register("int8_conv2d", no_grad=True)
def lower_int8_conv2d(ctx, ins):
    """int8 conv with int32 accumulation (NCHW, OIHW), rescaled to f32."""
    import jax.lax as lax

    jnp = _jnp()
    x, w = ins["Input"][0], ins["Filter"][0]
    sx = ins["ScaleX"][0].reshape(()) if ins.get("ScaleX") else 1.0
    sw = ins["ScaleW"][0].reshape(()) if ins.get("ScaleW") else 1.0
    s = ctx.attr("strides", [1, 1])
    p = ctx.attr("paddings", [0, 0])
    d = ctx.attr("dilations", [1, 1])
    g = ctx.attr("groups", 1) or 1
    fmt = ctx.attr("data_format", "NCHW")
    acc = lax.conv_general_dilated(
        x, w,
        window_strides=tuple(s),
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=tuple(d),
        dimension_numbers=(fmt, "OIHW", fmt),
        feature_group_count=g,
        preferred_element_type=jnp.int32,
    )
    return {"Out": [acc.astype(jnp.float32) * (sx * sw / (127.0 * 127.0))]}
