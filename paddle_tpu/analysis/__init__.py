"""Static-analysis tier: catch miscompiles BEFORE trace time.

The reference framework's C++ runtime validates every ProgramDesc op
against its registered shape/dtype/attr contract before execution
(operator.cc RuntimeInferShape ENFORCE, framework.proto IR); this package
is the TPU-first equivalent for the Python IR:

  * verifier.py — walks a Program through the op registry: def-before-use
    / SSA across blocks, static shape+dtype contract re-inference,
    dead-var/dead-op detection, donation/fetch alias conflicts, and the
    RNG-determinism lint (key-deriving ops the executor would not thread
    the step key for — the PR-4 `dropout_add` bug class).
  * costmodel.py — static roofline / launch-cost model: per-op analytic
    FLOPs + HBM bytes from the declared IR shapes, compute/memory/launch
    classification against a declared device model, and the predicted
    step time `max(flops/peak, bytes/bw) + n_launches*overhead` that
    tools/perf_report.py renders (ROADMAP item 1's launch-bound
    fraction).
  * numerics.py — the FLAGS_check_numerics instrumentation pass: rewrite
    a Program to append fused per-tensor health reductions
    (ops/numerics_ops.py) packed into one [N, 4] stats fetch per step —
    per-op-output rows in `locate` mode (NaN/Inf origin localization),
    grad/weight/update rows in `summary` mode (training-dynamics
    gauges); `off` is zero-cost with a byte-identical fingerprint.
  * kernel_lint.py — statically audits every Pallas kernel plan in
    kernels/ (attention, fused-qkv, conv_bn, dropout_epilogue, embedding,
    ring attention): VMEM budget vs the plan gate's estimate, (8,128)
    sublane/lane tile alignment, grid/block divisibility,
    input_output_aliases shape/dtype validity, and revisited-block
    accumulation dtypes — the checks that previously lived only in
    interpret-mode asserts until a chip run.

Wiring: Executor._maybe_verify (FLAGS_verify_program) gates every compile;
tools/graph_lint.py drives the full model matrix and emits the CI findings
artifact (ci_artifacts/graph_lint.json).
"""

from __future__ import annotations

from .verifier import (  # noqa: F401
    Finding,
    ProgramVerifyError,
    verify_or_raise,
    verify_program,
    verify_program_set,
)
from .kernel_lint import lint_kernel_plans  # noqa: F401
from .numerics import (  # noqa: F401
    instrument_program,
    is_instrumented,
    maybe_instrument,
)
from .costmodel import (  # noqa: F401
    DEVICE_MODELS,
    DeviceModel,
    OpCost,
    ProgramCost,
    cost_program,
    publish_cost,
    resolve_device_model,
)

__all__ = [
    "Finding",
    "ProgramVerifyError",
    "verify_program",
    "verify_or_raise",
    "verify_program_set",
    "lint_kernel_plans",
    "instrument_program",
    "is_instrumented",
    "maybe_instrument",
    "DEVICE_MODELS",
    "DeviceModel",
    "OpCost",
    "ProgramCost",
    "cost_program",
    "publish_cost",
    "resolve_device_model",
]
