"""Book-model integration tests (reference: tests/book/ — canonical small
models trained a few iterations with loss thresholds: fit_a_line,
image_classification, understand_sentiment, recommender_system; the other
book models are covered by test_mnist.py (recognize_digits),
test_beam_search.py (machine_translation), test_crf_nce.py (word2vec +
label_semantic_roles), test_data_feed.py (CTR)).  All datasets run in
synthetic offline mode."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(41)


def _train(loss_var, feeder, batches, lr=0.01, opt=None):
    (opt or pt.optimizer.AdamOptimizer(learning_rate=lr)).minimize(loss_var)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for feed in batches:
        (lv,) = exe.run(feed=feed, fetch_list=[loss_var])
        losses.append(float(np.asarray(lv)))
    return losses


def test_fit_a_line_uci_housing():
    """reference tests/book/test_fit_a_line.py."""
    data = list(pt.dataset.uci_housing.train(synthetic=True)())
    x = layers.data(name="x", shape=[13], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(pred - y))

    def batches(n_epochs=40, bs=64):
        for _ in range(n_epochs):
            for i in range(0, len(data) - bs, bs):
                chunk = data[i:i + bs]
                yield {"x": np.stack([c[0] for c in chunk]),
                       "y": np.stack([c[1] for c in chunk])}

    losses = _train(loss, None, batches(), lr=0.5)
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


@pytest.mark.slow
def test_image_classification_cifar_resnet():
    """reference tests/book/test_image_classification.py (resnet_cifar10)."""
    from paddle_tpu.models import resnet as R

    samples = list(pt.dataset.cifar.train10(synthetic=True)())
    img = layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = R.resnet_cifar10(img, class_dim=10, depth=20)
    loss = layers.mean(layers.cross_entropy(input=predict, label=label))

    def batches(n=30, bs=32):
        idx = rng.permutation(len(samples))
        for s in range(n):
            take = idx[(s * bs) % (len(samples) - bs):][:bs]
            yield {
                "img": np.stack(
                    [samples[i][0].reshape(3, 32, 32) for i in take]),
                "label": np.array(
                    [[samples[i][1]] for i in take], "int64"),
            }

    losses = _train(loss, None, batches(), lr=0.01)
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_understand_sentiment_imdb_lstm():
    """reference tests/book/test_understand_sentiment.py (dynamic LSTM)."""
    wd = pt.dataset.imdb.word_dict(synthetic=True)
    samples = list(pt.dataset.imdb.train(wd, synthetic=True)())
    t_max, vocab = 64, len(wd)

    word = layers.data(name="word", shape=[t_max, 1], dtype="int64")
    length = layers.data(name="len", shape=[1], dtype="int64")
    label = layers.data(name="label", shape=[1], dtype="int64")
    emb = layers.embedding(word, size=[vocab, 32])
    # dynamic_lstm wants the pre-projected [B, T, 4*hidden] input
    proj = layers.fc(emb, size=4 * 32, num_flatten_dims=2, bias_attr=False)
    h, _cell = layers.dynamic_lstm(proj, size=4 * 32, length=length)
    pooled = layers.sequence_pool(h, "last", length=length)
    logits = layers.fc(pooled, size=2)
    loss = layers.mean(layers.softmax_with_cross_entropy(
        logits=logits, label=layers.reshape(label, [-1, 1])))

    def batches(n=40, bs=32):
        for s in range(n):
            take = [samples[(s * bs + i) % len(samples)] for i in range(bs)]
            w = np.zeros((bs, t_max, 1), "int64")
            ln = np.zeros((bs,), "int64")
            lb = np.zeros((bs, 1), "int64")
            for i, (ids, y) in enumerate(take):
                k = min(len(ids), t_max)
                w[i, :k, 0] = ids[:k]
                ln[i] = k
                lb[i, 0] = y
            yield {"word": w, "len": ln, "label": lb}

    losses = _train(loss, None, batches(), lr=0.02)
    assert losses[-1] < losses[0] * 0.75, (losses[0], losses[-1])


def test_recommender_system_movielens():
    """reference tests/book/test_recommender_system.py — user/movie feature
    towers + fused features -> rating regression."""
    samples = list(pt.dataset.movielens.train(synthetic=True)())
    n_users = max(s[0] for s in samples) + 1
    n_movies = max(s[4] for s in samples) + 1
    n_cats = len(pt.dataset.movielens.movie_categories())

    uid = layers.data(name="uid", shape=[1], dtype="int64")
    gender = layers.data(name="gender", shape=[1], dtype="int64")
    age = layers.data(name="age", shape=[1], dtype="int64")
    job = layers.data(name="job", shape=[1], dtype="int64")
    mid = layers.data(name="mid", shape=[1], dtype="int64")
    cats = layers.data(name="cats", shape=[3, 1], dtype="int64")
    cats_len = layers.data(name="cats__len", shape=[1], dtype="int64")
    score = layers.data(name="score", shape=[1], dtype="float32")

    def tower(parts, size=16):
        feats = layers.concat(parts, axis=1)
        return layers.fc(feats, size=size, act="tanh")

    u = tower([
        layers.reshape(layers.embedding(
            layers.reshape(uid, [-1, 1, 1]), size=[n_users, 16]), [-1, 16]),
        layers.reshape(layers.embedding(
            layers.reshape(gender, [-1, 1, 1]), size=[2, 4]), [-1, 4]),
        layers.reshape(layers.embedding(
            layers.reshape(age, [-1, 1, 1]), size=[8, 4]), [-1, 4]),
        layers.reshape(layers.embedding(
            layers.reshape(job, [-1, 1, 1]), size=[21, 4]), [-1, 4]),
    ])
    cat_emb = layers.embedding(
        layers.reshape(cats, [-1, 3, 1]), size=[n_cats, 8])
    m = tower([
        layers.reshape(layers.embedding(
            layers.reshape(mid, [-1, 1, 1]), size=[n_movies, 16]), [-1, 16]),
        layers.sequence_pool(cat_emb, "sum", length=cats_len),
    ])
    pred = layers.reduce_sum(
        layers.elementwise_mul(u, m), dim=1, keep_dim=True)
    loss = layers.mean(layers.square(pred - score))

    def batches(n=60, bs=64):
        for s in range(n):
            take = [samples[(s * bs + i) % len(samples)] for i in range(bs)]
            cat_arr = np.zeros((bs, 3, 1), "int64")
            cat_len = np.zeros((bs,), "int64")
            for i, smp in enumerate(take):
                cs = smp[5][:3]
                cat_arr[i, :len(cs), 0] = cs
                cat_len[i] = len(cs)
            yield {
                "uid": np.array([[s[0]] for s in take], "int64"),
                "gender": np.array([[s[1]] for s in take], "int64"),
                "age": np.array([[s[2]] for s in take], "int64"),
                "job": np.array([[s[3]] for s in take], "int64"),
                "mid": np.array([[s[4]] for s in take], "int64"),
                "cats": cat_arr,
                "cats__len": cat_len,
                "score": np.array([[s[7]] for s in take], "float32"),
            }

    losses = _train(loss, None, batches(), lr=0.05)
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
