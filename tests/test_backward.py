"""Autodiff-machinery tests (reference: test_backward.py, test_calc_gradient.py)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def test_gradients_wrt_data_var():
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.reduce_mean(layers.square(x))
    (gx,) = pt.gradients(y, x)
    assert gx is not None
    exe = pt.Executor(pt.CPUPlace())
    xv = np.arange(8, dtype="float32").reshape(2, 4)
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv / xv.size, rtol=1e-5)


def test_repeated_use_accumulates():
    # x used by two consumers: grads must sum
    x = layers.data(name="x", shape=[3], dtype="float32")
    a = layers.scale(x, scale=2.0)
    b = layers.scale(x, scale=3.0)
    s = layers.elementwise_add(a, b)
    loss = layers.reduce_sum(s)
    (gx,) = pt.gradients(loss, x)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((2, 3), np.float32)
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, np.full((2, 3), 5.0), rtol=1e-6)


def test_stop_gradient_blocks():
    x = layers.data(name="x", shape=[3], dtype="float32")
    w = layers.data(name="w", shape=[3], dtype="float32")
    w.stop_gradient = True
    y = layers.elementwise_mul(x, w)
    loss = layers.reduce_sum(y)
    pg = pt.append_backward(loss)
    blk = pt.default_main_program().global_block()
    assert not blk.has_var_recursive(pt.grad_var_name("w"))


def test_dropout_seed_reproducible():
    x = layers.data(name="x", shape=[100], dtype="float32")
    out = layers.dropout(x, dropout_prob=0.5, seed=1234)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((4, 100), np.float32)
    (o1,) = exe.run(feed={"x": xv}, fetch_list=[out])
    (o2,) = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_array_equal(o1, o2)  # fixed seed -> same mask
    assert (o1 == 0).mean() > 0.2  # dropout actually active


def test_dropout_no_seed_varies():
    x = layers.data(name="x", shape=[100], dtype="float32")
    out = layers.dropout(x, dropout_prob=0.5)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((4, 100), np.float32)
    (o1,) = exe.run(feed={"x": xv}, fetch_list=[out])
    (o2,) = exe.run(feed={"x": xv}, fetch_list=[out])
    assert (np.asarray(o1) != np.asarray(o2)).any()


def test_dropout_grad_uses_mask():
    x = layers.data(name="x", shape=[50], dtype="float32")
    x.stop_gradient = False
    x.is_data = False
    out = layers.dropout(x, dropout_prob=0.3,
                         dropout_implementation="upscale_in_train")
    loss = layers.reduce_sum(out)
    (gx,) = pt.gradients(loss, x)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((4, 50), np.float32)
    g, o = exe.run(feed={"x": xv}, fetch_list=[gx, out])
    # grad == mask: zero where dropped, 1/(1-p) where kept
    np.testing.assert_allclose(g, np.asarray(o), rtol=1e-6)


def test_cumsum_exclusive_reverse():
    x = layers.data(name="x", shape=[3], dtype="float32")
    out = layers.cumsum(x, axis=-1, exclusive=True, reverse=True)
    exe = pt.Executor(pt.CPUPlace())
    (o,) = exe.run(feed={"x": np.array([[1, 2, 3]], np.float32)}, fetch_list=[out])
    np.testing.assert_allclose(o, [[5, 3, 0]])


def test_conv2d_transpose_groups():
    x = layers.data(name="x", shape=[4, 5, 5], dtype="float32")
    out = layers.conv2d_transpose(x, num_filters=8, filter_size=3, groups=2,
                                  stride=2, bias_attr=False)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    (o,) = exe.run(feed={"x": np.random.rand(2, 4, 5, 5).astype("float32")},
                   fetch_list=[out])
    assert o.shape == (2, 8, 11, 11), o.shape
