"""PipelineProgram: micro-batch pipeline training through the executor.

The multi-program counterpart of Executor.run_accumulated: feed arrays
carry a leading [K, micro_bs, ...] axis; one train step walks a
GPipe/1F1B tick table (schedule.py) driving per-stage compiled entries
— forward with activation stashing, backward with boundary-grad routing,
then each stage's LOCAL optimizer once on its averaged grads.

The parity contract vs run_accumulated on the unsplit program
(asserted in tests/test_pipeline.py with dropout on): TRAINING STATE —
every parameter and optimizer-state update — is BIT-IDENTICAL; the
fetched loss trajectory agrees to the last ulp.  (The carve-out is a
measured XLA CPU property: a reduce feeding only a fetched scalar may
tile differently across separately compiled modules and re-round by one
ulp on tie values; state never drifts — probed per-gradient.  PERF.md
round 11.)  The mechanics:

  * micro-batch m's traces use fold_in(step_key, m), the optimizer
    fold_in(step_key, K) — the exact run_accumulated key schedule; all
    bundled random ops key on static per-op rng_id attrs, so stage-split
    traces regenerate the same masks;
  * per-stage grad accumulation adds micro-batches in 0..K-1 order
    (both schedules guarantee per-stage mb order) and averages by
    /float(K), matching the scan in _compile_accumulated;
  * split_program marks boundary-crossing producers with optimization
    barriers honored by BOTH compilations, normalizing cut-point reduce
    association (partition.py).

Runs via exe.run delegation (the ShardedProgram _run-hook pattern):

    pipe = PipelineProgram(prog, feed_names, n_stages=2, schedule="1f1b")
    losses = exe.run(pipe, feed={...}, fetch_list=[loss], scope=scope)

rw scope state (e.g. BatchNorm running stats) threads through each
stage's forward in micro-batch order and is donated per call, exactly
like run_accumulated's scan carry; optimizer rw buffers are donated to
the per-stage optimizer entries.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core import executor as exec_mod
from ...core import framework as fw
from ...core.executor import prng_key as _prng_key
from . import schedule as sched_mod
from .partition import PipelineStage, PipelineStages, split_program


def _phase_state(ops, scope, skip_names) -> Tuple[List[str], List[str]]:
    """(reads, writes) of scope-resident names for an op subset — the
    per-phase analogue of analyze_block_io."""
    defined = set(skip_names)
    reads, writes = [], []
    seen_r, seen_w = set(), set()
    for op in ops:
        for n in op.input_arg_names():
            if n and n not in defined and n not in seen_r \
                    and scope.find_var(n) is not None:
                reads.append(n)
                seen_r.add(n)
                defined.add(n)
        for n in op.output_arg_names():
            if not n:
                continue
            defined.add(n)
            v = op.block._find_var_recursive(n)
            if ((v is not None and v.persistable) or scope.has_var(n)) \
                    and n not in seen_w:
                writes.append(n)
                seen_w.add(n)
    return reads, writes


class _StageEntry:
    """Compiled fwd/bwd/opt callables + their name lists for one stage."""

    __slots__ = ("fwd", "bwd", "opt", "fwd_rw", "fwd_ro", "bwd_ro",
                 "opt_rw", "opt_ro", "opt_writes", "fwd_fetch",
                 "bwd_fetch", "opt_fetch")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class PipelineProgram:
    def __init__(
        self,
        program: fw.Program,
        feed_names: Sequence[str],
        n_stages: int = 2,
        cut_vars: Optional[Sequence[str]] = None,
        schedule: str = "gpipe",
        stages: Optional[PipelineStages] = None,
        plan=None,
    ):
        """plan: optional parallel.sharding.ShardingPlan over dp/tp mesh
        axes — each stage's compiled entries then carry GSPMD shardings
        (feeds over the data axis, params by the plan's rules), so the
        schedule time-multiplexes pp stages over a dp x tp device mesh:
        the dryrun matrix's dp x tp x pp composition.  Sharded entries
        skip buffer donation (the scope holds unsharded arrays between
        steps; donating a to-be-resharded buffer is a copy anyway) and
        the parity contract relaxes to allclose — collectives reassociate
        reductions.

        `schedule` is mutable between steps: compiled stage entries are
        schedule-independent (the tick table is consulted per step), so
        swapping gpipe <-> 1f1b on one instance reuses every entry."""
        if schedule not in sched_mod.SCHEDULES:
            raise ValueError(
                f"unknown schedule {schedule!r}; one of "
                f"{sched_mod.SCHEDULES}")
        self.schedule = schedule
        self.stages = stages if stages is not None else split_program(
            program, feed_names, n_stages=n_stages, cut_vars=cut_vars)
        self.program = program
        self.feed_names = list(feed_names)
        self.plan = plan
        self._mesh = None
        self._cache: Dict[Any, List[_StageEntry]] = {}
        self._ref_names = None
        self._verified = set()

    @property
    def mesh(self):
        if self.plan is not None and self._mesh is None:
            self._mesh = self.plan.build_mesh()
        return self._mesh

    def _scope_signature(self, scope) -> frozenset:
        """Which stage-referenced names resolve to a live scope var —
        part of the compile-cache AND verify keys: _compile_stage bakes
        the scope-dependent rw/ro state split into the jitted entries,
        so a differently-populated scope must recompile, not hit a stale
        entry (the executor's _scope_signature contract, PR 9's memo
        class)."""
        if self._ref_names is None:
            seen = set()
            for st in self.stages:
                for op in st.program.global_block().ops:
                    for n in op.input_arg_names() + op.output_arg_names():
                        if n:
                            seen.add(n)
            self._ref_names = tuple(seen)
        return frozenset(n for n in self._ref_names
                         if scope.find_var(n) is not None)

    # -- verification -----------------------------------------------------
    def _maybe_verify(self, scope, scope_sig):
        from ...flags import FLAGS

        if scope_sig in self._verified or not FLAGS.verify_program:
            return
        from ...analysis import verify_or_raise, verify_program_set

        for st in self.stages:
            feedish = (st.feeds + [n for n, _, _ in st.fwd_inputs]
                       + [n for n, _, _ in st.bwd_inputs] + st.bwd_feeds)
            fetch = ([n for n, _, _ in st.fwd_outputs]
                     + [n for n, _, _ in st.bwd_outputs])
            verify_or_raise(st.program, feed_names=feedish,
                            fetch_names=fetch, scope=scope)
        findings = verify_program_set(
            [st.io_summary() for st in self.stages])
        errors = [f for f in findings if f.severity == "error"]
        if errors:
            from ...analysis import ProgramVerifyError

            raise ProgramVerifyError(findings)
        self._verified.add(scope_sig)

    # -- compile ----------------------------------------------------------
    def _compile_stage(self, st: PipelineStage, scope, fetch_names):
        import jax

        block = st.program.global_block()
        fwd_ops, bwd_ops, opt_ops = st.fwd_ops(), st.bwd_ops(), st.opt_ops()
        fwd_in_names = [n for n, _, _ in st.fwd_inputs]
        fwd_out_names = [n for n, _, _ in st.fwd_outputs]
        bwd_in_names = [n for n, _, _ in st.bwd_inputs]
        bwd_out_names = [n for n, _, _ in st.bwd_outputs]

        fwd_reads, fwd_writes = _phase_state(
            fwd_ops, scope, st.feeds + fwd_in_names)
        fwd_rw = [n for n in fwd_reads if n in set(fwd_writes)]
        fwd_ro = [n for n in fwd_reads if n not in set(fwd_rw)]
        # params the grad ops re-read (matmul_grad reads W) ride bwd_ro
        # from the scope — within a step their value is fwd-time exact
        bwd_ro, bwd_writes = _phase_state(
            bwd_ops, scope, st.stash + bwd_in_names + st.bwd_feeds)
        if bwd_writes:
            raise NotImplementedError(
                f"pipeline stage {st.index}: backward ops write scope "
                f"state {bwd_writes[:4]} — not supported (grads must stay "
                f"program-local)")
        opt_reads, opt_writes = _phase_state(
            opt_ops, scope, st.grad_names)
        opt_rw = [n for n in opt_reads if n in set(opt_writes)]
        opt_ro = [n for n in opt_reads if n not in set(opt_rw)]
        # write-only opt outputs (fresh moment vars) surface too
        opt_writes = opt_rw + [n for n in opt_writes if n not in set(opt_rw)]

        fwd_fetch = [n for n in fetch_names
                     if n in st.fetch_candidates
                     or n in set(st.feeds) | set(fwd_in_names)]
        bwd_produced = {n for op in bwd_ops
                        for n in op.output_arg_names() if n}
        bwd_fetch = [n for n in fetch_names
                     if n in bwd_produced and n not in set(fwd_fetch)]
        opt_produced = {n for op in opt_ops
                        for n in op.output_arg_names() if n}
        opt_fetch = [n for n in fetch_names
                     if n in opt_produced
                     and n not in set(fwd_fetch) | set(bwd_fetch)]

        is_test = getattr(st.program, "_is_test", False)

        def fwd_fn(feed_vals, in_vals, rw_vals, ro_vals, key):
            tctx = exec_mod.TraceContext(st.program, key, is_test=is_test)
            env: Dict[str, Any] = {}
            env.update(zip(st.feeds, feed_vals))
            env.update(zip(fwd_in_names, in_vals))
            env.update(zip(fwd_rw, rw_vals))
            env.update(zip(fwd_ro, ro_vals))
            exec_mod.trace_block(block, env, tctx, ops=fwd_ops)
            # fetch values barriered like run_accumulated's (the
            # association-isolation half of the bit-parity contract)
            return (
                [env[n] for n in fwd_out_names],
                [env[n] for n in st.stash],
                [jax.lax.optimization_barrier(env[n])
                 for n in fwd_fetch],
                [env.get(n, v) for n, v in zip(fwd_rw, rw_vals)],
            )

        def bwd_fn(stash_vals, gin_vals, bfeed_vals, ro_vals, key):
            tctx = exec_mod.TraceContext(st.program, key, is_test=is_test)
            env: Dict[str, Any] = {}
            env.update(zip(st.stash, stash_vals))
            env.update(zip(bwd_in_names, gin_vals))
            env.update(zip(st.bwd_feeds, bfeed_vals))
            env.update(zip(bwd_ro, ro_vals))
            exec_mod.trace_block(block, env, tctx, ops=bwd_ops)
            return (
                [env[n] for n in bwd_out_names],
                [env[n] for n in st.grad_names],
                [jax.lax.optimization_barrier(env[n])
                 for n in bwd_fetch],
            )

        def opt_fn(grad_avgs, rw_vals, ro_vals, key):
            tctx = exec_mod.TraceContext(st.program, key, is_test=is_test)
            env: Dict[str, Any] = {}
            env.update(zip(opt_rw, rw_vals))
            env.update(zip(opt_ro, ro_vals))
            env.update(zip(st.grad_names, grad_avgs))
            exec_mod.trace_block(block, env, tctx, ops=opt_ops)
            return (
                [env.get(n) for n in opt_writes],
                [env.get(n) for n in opt_fetch],
            )

        if self.plan is not None:
            from jax.sharding import NamedSharding

            mesh = self.mesh
            params = {p.name for p in st.program.all_parameters()}

            def shard_of(n):
                v = scope.find_var(n)
                return NamedSharding(mesh, self.plan.spec_for_param(
                    n, getattr(v, "shape", None),
                    is_moment=n not in params))

            feed_sh = [NamedSharding(mesh, self.plan.spec_for_feed(n))
                       for n in st.feeds]
            bfeed_sh = [NamedSharding(mesh, self.plan.spec_for_feed(n))
                        for n in st.bwd_feeds]
            fwd_jit = jax.jit(fwd_fn, in_shardings=(
                feed_sh, None, [shard_of(n) for n in fwd_rw],
                [shard_of(n) for n in fwd_ro], None))
            bwd_jit = jax.jit(bwd_fn, in_shardings=(
                None, None, bfeed_sh,
                [shard_of(n) for n in bwd_ro], None))
            opt_jit = jax.jit(opt_fn, in_shardings=(
                None, [shard_of(n) for n in opt_rw],
                [shard_of(n) for n in opt_ro], None),
                out_shardings=([shard_of(n) for n in opt_writes],
                               None)) if opt_ops else None
            return _StageEntry(
                fwd=fwd_jit, bwd=bwd_jit, opt=opt_jit,
                fwd_rw=fwd_rw, fwd_ro=fwd_ro, bwd_ro=bwd_ro,
                opt_rw=opt_rw, opt_ro=opt_ro, opt_writes=opt_writes,
                fwd_fetch=fwd_fetch, bwd_fetch=bwd_fetch,
                opt_fetch=opt_fetch,
            )
        return _StageEntry(
            fwd=jax.jit(fwd_fn, donate_argnums=(2,)),
            bwd=jax.jit(bwd_fn),
            opt=jax.jit(opt_fn, donate_argnums=(1,)) if opt_ops else None,
            fwd_rw=fwd_rw, fwd_ro=fwd_ro, bwd_ro=bwd_ro,
            opt_rw=opt_rw, opt_ro=opt_ro, opt_writes=opt_writes,
            fwd_fetch=fwd_fetch, bwd_fetch=bwd_fetch, opt_fetch=opt_fetch,
        )

    # -- execution (exe.run delegates here) -------------------------------
    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        import jax

        feed = feed or {}
        scope = scope or exec_mod.global_scope()
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v
            for v in (fetch_list or [])
        ]
        if not feed:
            raise ValueError("PipelineProgram needs a [K, micro_bs, ...] "
                             "feed to derive the micro-batch count")
        feed_stack = {
            n: executor._to_device_array(self.program, n, feed[n])
            for n in sorted(feed)
        }
        k = int(next(iter(feed_stack.values())).shape[0])
        for n, v in feed_stack.items():
            if int(v.shape[0]) != k:
                raise ValueError(
                    f"feed {n!r} leading dim {v.shape[0]} != micro-batch "
                    f"count {k}")

        scope_sig = self._scope_signature(scope)
        self._maybe_verify(scope, scope_sig)
        key = (k, scope_sig, tuple(sorted(feed_stack)),
               tuple((tuple(v.shape), str(v.dtype))
                     for _, v in sorted(feed_stack.items())),
               tuple(fetch_names))
        entries = self._cache.get(key)
        if entries is None:
            # unresolvable fetches fail loudly before any compile
            known = set(feed_stack) | {
                n for st in self.stages
                for n in (st.fetch_candidates
                          | {o for op in st.bwd_ops()
                             for o in op.output_arg_names() if o}
                          | {o for op in st.opt_ops()
                             for o in op.output_arg_names() if o})}
            missing = [n for n in fetch_names if n not in known]
            if missing:
                raise KeyError(
                    f"fetch target(s) {missing} produced by no pipeline "
                    f"stage (fwd/bwd/optimizer) and covered by no feed")
            entries = [self._compile_stage(st, scope, fetch_names)
                       for st in self.stages]
            self._cache[key] = entries

        S = self.stages.n_stages
        ticks = sched_mod.schedule_table(S, k, self.schedule)

        # the step key draws the DELEGATING executor's run counter —
        # run_accumulated on the unsplit program draws the same source,
        # so trajectories line up call-for-call (bit-parity contract)
        base_key = jax.random.fold_in(
            _prng_key(self.program.random_seed or 0),
            executor._next_run_id())
        mb_keys = [jax.random.fold_in(base_key, m) for m in range(k)]

        from ...monitor import enabled as _mon_enabled

        mon = _mon_enabled()
        if mon:
            from ...monitor import flight as _flight
        boundary: List[Dict[str, Any]] = [dict() for _ in range(k)]
        grad_env: List[Dict[str, Any]] = [dict() for _ in range(k)]
        stash: Dict[Tuple[int, int], list] = {}
        grad_sums: List[Optional[list]] = [None] * S
        fetch_store: Dict[Tuple[str, int], Any] = {}
        rw_vals = [[scope.find_var(n) for n in entries[s].fwd_rw]
                   for s in range(S)]
        in_flight = [0] * S
        peak_in_flight = 0

        for tick in ticks:
            for s, phase, m in tick:
                st, en = self.stages.stages[s], entries[s]
                t0 = time.perf_counter() if mon else 0.0
                if phase == "fwd":
                    feeds_m = [feed_stack[n][m] for n in st.feeds]
                    ins_m = [boundary[m][n]
                             for n, _, _ in st.fwd_inputs]
                    ro = [scope.find_var(n) for n in en.fwd_ro]
                    outs, stvals, fvals, new_rw = en.fwd(
                        feeds_m, ins_m, rw_vals[s], ro, mb_keys[m])
                    rw_vals[s] = new_rw
                    # keep the scope current: the fwd entry donated the
                    # previous rw buffers, and another phase reading the
                    # scope must never see a deleted array
                    for n, v in zip(en.fwd_rw, new_rw):
                        scope.set_var(n, v)
                    for (n, _, _), v in zip(st.fwd_outputs, outs):
                        boundary[m][n] = v
                    stash[(s, m)] = stvals
                    for n, v in zip(en.fwd_fetch, fvals):
                        fetch_store[(n, m)] = v
                    in_flight[s] += 1
                    peak_in_flight = max(peak_in_flight, in_flight[s])
                else:
                    gins = [grad_env[m][n] for n, _, _ in st.bwd_inputs]
                    bfeeds = [feed_stack[n][m] for n in st.bwd_feeds]
                    ro = [scope.find_var(n) for n in en.bwd_ro]
                    gouts, gvals, bfvals = en.bwd(
                        stash.pop((s, m)), gins, bfeeds, ro, mb_keys[m])
                    for (n, _, _), v in zip(st.bwd_outputs, gouts):
                        grad_env[m][n] = v
                    # accumulate in micro-batch order: bit-identical to
                    # run_accumulated's scan (sums0 + g1 + g2 + ...)
                    if grad_sums[s] is None:
                        grad_sums[s] = list(gvals)
                    else:
                        grad_sums[s] = [a + b for a, b in
                                        zip(grad_sums[s], gvals)]
                    for n, v in zip(en.bwd_fetch, bfvals):
                        fetch_store[(n, m)] = v
                    in_flight[s] -= 1
                if mon:
                    with _flight.context(f"pipeline/{s}"):
                        _flight.record(
                            "pipeline.stage", stage=s, phase=phase, mb=m,
                            t0=t0 + (time.time() - time.perf_counter()),
                            dur=round(time.perf_counter() - t0, 6))

        # optimizer: once per stage on its averaged local grads, exactly
        # the run_accumulated suffix (key fold_in(base, K), sums/float(K))
        opt_key = jax.random.fold_in(base_key, k)
        for s in range(S):
            en, st = entries[s], self.stages.stages[s]
            # final fwd rw writes land before the optimizer (scan-carry
            # order parity with _compile_accumulated)
            for n, v in zip(en.fwd_rw, rw_vals[s]):
                scope.set_var(n, v)
            if en.opt is None:
                continue
            sums = grad_sums[s] or []
            avgs = [g / float(k) for g in sums]
            opt_rw_vals = [scope.find_var(n) for n in en.opt_rw]
            opt_ro_vals = [scope.find_var(n) for n in en.opt_ro]
            new_state, ofvals = en.opt(avgs, opt_rw_vals, opt_ro_vals,
                                       opt_key)
            for n, v in zip(en.opt_writes, new_state):
                if v is not None:
                    scope.set_var(n, v)
            for n, v in zip(en.opt_fetch, ofvals):
                fetch_store[(n, None)] = v

        if mon:
            from ... import monitor
            from ...monitor import flight as _flight

            bf = sched_mod.bubble_fraction(S, k, self.schedule)
            monitor.gauge("pipeline.bubble_fraction").set(bf)
            monitor.gauge("pipeline.microbatches_in_flight").set(
                peak_in_flight)
            _flight.record("pipeline.schedule", schedule=self.schedule,
                           n_stages=S, n_micro=k,
                           bubble_fraction=round(bf, 4),
                           peak_in_flight=peak_in_flight)

        import jax.numpy as jnp

        outs = []
        for n in fetch_names:
            if (n, None) in fetch_store:
                outs.append(fetch_store[(n, None)])
            elif (n, 0) in fetch_store:
                outs.append(jnp.stack([fetch_store[(n, m)]
                                       for m in range(k)]))
            elif n in feed_stack:
                outs.append(feed_stack[n])
            else:  # pragma: no cover — guarded by the compile-time check
                raise KeyError(f"fetch target {n!r} not produced")
        if return_numpy:
            return [np.asarray(v) for v in outs]
        return list(outs)
