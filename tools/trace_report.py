#!/usr/bin/env python
"""Summarize a unified chrome trace (profiler.export_unified_chrome_trace)
— the text-report half of the timeline tentpole:

  * top device ops by total time (per-device xplane tracks; host XLA
    lines when the trace has no device plane, e.g. the CPU mesh),
  * compile vs run vs feed-stall host time (the "where did the wall
    clock go" breakdown, from the flight spans),
  * recompile causes (which cache-key component churned, aggregated),
  * a "Requests" section from the request-scoped traces
    (monitor/tracing.py trace.request events): slowest traces with their
    latency decomposition, and the padding-waste top-K (rows padded vs
    real — wasted compute attributed per request),
  * watchdog trips and the last completed step (from the embedded
    flight header).

Usage: python tools/trace_report.py merged_trace.json [--top 20]

Also accepts a raw jax trace DIRECTORY (the start_profiler trace_dir):
then only the device-op table is available.  Plain stdlib — the report
must be runnable on the barest postmortem host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict


def load_trace(path: str) -> dict:
    if os.path.isdir(path):
        # raw jax trace dir: build the xplane-only event list in-process
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".."))
        from paddle_tpu.profiler import _xplane_chrome_events

        return {"traceEvents": _xplane_chrome_events(path, 500000)}
    with open(path) as f:
        return json.load(f)


def _index_processes(events):
    """pid -> {"name": ..., "device": bool, "source": ...}."""
    procs = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            procs[ev["pid"]] = dict(ev.get("args", {}))
    return procs


def top_ops(doc: dict, k: int = 20):
    """(rows, scope): rows of (op_name, total_s, calls) over device-plane
    events; falls back to host XLA runtime lines on device-less traces."""
    events = doc.get("traceEvents", [])
    procs = _index_processes(events)
    device_pids = {p for p, a in procs.items() if a.get("device")}
    xplane_pids = {p for p, a in procs.items()
                   if a.get("source") == "xplane"}
    scope = "device"
    pids = device_pids
    if not pids:
        scope, pids = "host-xplane", xplane_pids
    agg = defaultdict(lambda: [0.0, 0])
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in pids:
            continue
        dur = float(ev.get("dur", 0.0))
        if dur <= 0:
            continue
        a = agg[ev.get("name", "?")]
        a[0] += dur / 1e6
        a[1] += 1
    rows = sorted(((n, t, c) for n, (t, c) in agg.items()),
                  key=lambda r: -r[1])[:k]
    return rows, scope


def host_breakdown(doc: dict):
    """Compile / run / feed-stall / step seconds from the flight spans."""
    fl = doc.get("flight", {})
    agg = defaultdict(lambda: [0.0, 0])
    for ev in fl.get("events", []):
        if "dur" not in ev:
            continue
        kind = ev.get("kind", "?")
        if kind.startswith("executor.compile"):
            key = "compile"
        elif kind.startswith("executor."):
            key = "run"
        elif kind.startswith("feed."):
            key = "feed_stall"
        elif kind == "step":
            key = "step"
        else:
            key = kind
        agg[key][0] += float(ev["dur"])
        agg[key][1] += 1
    return dict(agg)


def recompile_causes(doc: dict):
    agg = defaultdict(int)
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "executor.recompile":
            for comp in ev.get("changed", []):
                agg[comp] += 1
    return dict(agg)


def watchdog_trips(doc: dict):
    return [ev for ev in doc.get("flight", {}).get("events", [])
            if ev.get("kind") == "watchdog.trip"]


def numerics_info(doc: dict):
    """(locate verdict, last summary event, locate events) from the
    numerics tier (monitor/numerics.py): the header provider embeds the
    NaN-origin verdict; `numerics.summary` events carry the per-step
    training-dynamics aggregates."""
    hdr = doc.get("flight", {}).get("header", {})
    verdict = hdr.get("numerics")
    last_summary = None
    locates = []
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "numerics.summary":
            last_summary = ev
        elif ev.get("kind") == "numerics.locate":
            locates.append(ev)
    if verdict is None and locates:
        verdict = locates[-1]
    return verdict, last_summary, locates


def request_traces(doc: dict, k: int = 10):
    """(all trace.request events, slowest-K, padding-waste top-K) from
    the request-scoped tracing tier (monitor/tracing.py)."""
    reqs = [ev for ev in doc.get("flight", {}).get("events", [])
            if ev.get("kind") == "trace.request"]
    slowest = sorted(reqs, key=lambda e: -float(e.get("dur", 0.0)))[:k]
    padded = sorted((e for e in reqs if e.get("padded_rows")),
                    key=lambda e: -int(e.get("padded_rows", 0)))[:k]
    return reqs, slowest, padded


def pipeline_stages(doc: dict):
    """Per-stage span aggregation + the last schedule summary from the
    pipeline tier's flight events (parallel/pipeline/trainer.py:
    `pipeline.stage` spans carry ctx `pipeline/<stage>`;
    `pipeline.schedule` carries bubble-fraction / in-flight gauges)."""
    stages = defaultdict(lambda: defaultdict(lambda: [0.0, 0]))
    sched = None
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "pipeline.stage":
            agg = stages[ev.get("ctx", f"pipeline/{ev.get('stage')}")]
            a = agg[ev.get("phase", "?")]
            a[0] += float(ev.get("dur", 0.0))
            a[1] += 1
        elif ev.get("kind") == "pipeline.schedule":
            sched = ev
    return {k: {p: tuple(v) for p, v in d.items()}
            for k, d in stages.items()}, sched


def memory_plans(doc: dict):
    """Last memory.plan event per plan name (memory/planner.py
    publish_plan: peak watermark + per-class split + offloaded bytes)."""
    plans = {}
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "memory.plan":
            plans[ev.get("name", "main")] = ev
    return plans


def cost_attribution(doc: dict):
    """Last `cost.program` event per program name (analysis/costmodel
    publish_cost: the static roofline's predicted step time, launch-bound
    fraction, and bound-class census)."""
    costs = {}
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "cost.program":
            costs[ev.get("name", "?")] = ev
    return costs


def dispatch_split(doc: dict):
    """(dispatch_s, device_wait_s, n) summed over executor run spans that
    carry the enqueue-vs-transfer decomposition (core/executor.py)."""
    dispatch = wait = 0.0
    n = 0
    for ev in doc.get("flight", {}).get("events", []):
        if str(ev.get("kind", "")).startswith("executor.") \
                and "dispatch_s" in ev:
            dispatch += float(ev["dispatch_s"])
            wait += float(ev.get("device_wait_s", 0.0))
            n += 1
    return dispatch, wait, n


def embedding_census(doc: dict):
    """Last sparse-tier trace census (gather launches / rows touched per
    step — the embedding.* gauges, mirrored into the flight ring at
    trace time by core/executor.py)."""
    last = None
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") == "embedding.census":
            last = ev
    return last


def kv_page_activity(doc: dict):
    """Per-model aggregation of the paged-KV-cache `kv.page` flight
    events (serving/generation.py ContinuousBatcher: block alloc/free,
    shared-prefix hits, copy-on-write copies)."""
    agg = {}
    for ev in doc.get("flight", {}).get("events", []):
        if ev.get("kind") != "kv.page":
            continue
        a = agg.setdefault(ev.get("model", "?"),
                           {"alloc": 0, "hit": 0, "free": 0, "cow": 0,
                            "blocks_alloc": 0, "blocks_shared": 0})
        event = ev.get("event", "?")
        if event == "alloc":
            a["alloc"] += 1
            a["blocks_alloc"] += (int(ev.get("self_blocks", 0))
                                  + int(ev.get("cross_blocks", 0)))
        elif event == "hit":
            a["hit"] += 1
            a["blocks_alloc"] += int(ev.get("self_blocks", 0))
            a["blocks_shared"] += int(ev.get("shared_blocks", 0))
        elif event == "free":
            a["free"] += 1
        elif event == "cow":
            a["cow"] += int(ev.get("copies", 1))
    return agg


def report(doc: dict, k: int = 20) -> str:
    lines = []
    hdr = doc.get("flight", {}).get("header", {})
    if hdr:
        lines.append(
            f"run: pid={hdr.get('pid')} backend={hdr.get('jax_backend')} "
            f"devices={hdr.get('jax_device_count')} "
            f"last_step={hdr.get('last_step')} "
            f"last_loss={hdr.get('last_loss')}")

    rows, scope = top_ops(doc, k)
    lines.append("")
    lines.append(f"Top ops by total time ({scope} tracks)")
    lines.append(f"{'op':<56} {'total(s)':>10} {'calls':>8}")
    for name, total, calls in rows:
        lines.append(f"{name[:56]:<56} {total:>10.6f} {calls:>8}")
    if not rows:
        lines.append("(no xplane events in this trace)")

    bd = host_breakdown(doc)
    lines.append("")
    lines.append("Host time breakdown (flight spans)")
    if bd:
        lines.append(f"{'category':<16} {'total(s)':>10} {'spans':>8}")
        order = ("compile", "run", "step", "feed_stall")
        for key in [o for o in order if o in bd] + sorted(
                set(bd) - set(order)):
            t, c = bd[key]
            lines.append(f"{key:<16} {t:>10.4f} {c:>8}")
    else:
        lines.append("(no flight spans — was FLAGS.monitor on?)")

    causes = recompile_causes(doc)
    lines.append("")
    if causes:
        lines.append("Recompile causes (changed cache-key components)")
        for comp, n in sorted(causes.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {comp:<32} x{n}")
    else:
        lines.append("Recompiles: none recorded")

    costs = cost_attribution(doc)
    disp, wait, nrun = dispatch_split(doc)
    if costs or nrun:
        lines.append("")
        lines.append("Attribution (static cost model + dispatch split)")
    if costs:
        lines.append(
            f"{'program':<28} {'launches':>8} {'pred(us)':>10} "
            f"{'launch%':>8} {'bound c/m/l':>12}  device")
        for name in sorted(costs):
            ev = costs[name]
            bc = ev.get("bound_counts") or {}
            lines.append(
                f"{name[:28]:<28} {ev.get('n_launches', 0):>8} "
                f"{float(ev.get('predicted_seconds', 0)) * 1e6:>10.1f} "
                f"{float(ev.get('launch_bound_fraction', 0)):>8.1%} "
                f"{bc.get('compute', 0):>4}/{bc.get('memory', 0)}"
                f"/{bc.get('launch', 0):<5} "
                f"{ev.get('device', '?')} ({ev.get('device_source', '?')})")
    if nrun:
        tot = disp + wait
        frac = disp / tot if tot > 0 else 0.0
        lines.append(
            f"  executor split over {nrun} runs: dispatch {disp:.4f}s vs "
            f"device-wait {wait:.4f}s ({frac:.1%} host-side dispatch)")

    census = embedding_census(doc)
    if census:
        lines.append("")
        lines.append("Sparse embedding census (per traced step)")
        lines.append(f"  gather launches      {census.get('gather_launches')}")
        lines.append(
            f"  sparse rows touched  {census.get('sparse_rows_touched')}")

    plans = memory_plans(doc)
    if plans:
        lines.append("")
        lines.append("Memory (planner table, memory.plan events)")
        lines.append(
            f"{'plan':<14} {'peak MB':>9} {'act MB':>9} {'offl MB':>9} "
            f"{'peak op':<24} {'warn':>5}")
        for name in sorted(plans):
            ev = plans[name]
            by = ev.get("peak_by_class") or {}
            lines.append(
                f"{name[:14]:<14} "
                f"{float(ev.get('peak_bytes', 0)) / 1e6:>9.2f} "
                f"{float(ev.get('activation_peak_bytes', 0)) / 1e6:>9.2f} "
                f"{float(ev.get('offloaded_bytes', 0)) / 1e6:>9.2f} "
                f"{str(ev.get('peak_op_type', '?'))[:20]:<20} "
                f"@{ev.get('peak_op_index', '?'):<4} "
                f"{ev.get('warnings', 0):>4}")
            if by:
                lines.append("    at peak: " + ", ".join(
                    f"{c} {float(by.get(c, 0)) / 1e6:.2f} MB"
                    for c in ("params", "opt_state", "kv_cache",
                              "activations", "workspace", "feeds")
                    if by.get(c)))

    stages, sched = pipeline_stages(doc)
    if stages or sched:
        lines.append("")
        lines.append("Pipeline stages (flight spans)")
        if sched:
            lines.append(
                f"  schedule {sched.get('schedule')}: "
                f"{sched.get('n_stages')} stages x "
                f"{sched.get('n_micro')} micro-batches, bubble fraction "
                f"{sched.get('bubble_fraction')}, peak in-flight "
                f"{sched.get('peak_in_flight')}")
        for ctx in sorted(stages):
            parts = ", ".join(
                f"{p}: {t:.4f}s/{c}" for p, (t, c) in
                sorted(stages[ctx].items()))
            lines.append(f"  {ctx:<16} {parts}")

    reqs, slowest, padded = request_traces(doc, k)
    if reqs:
        lines.append("")
        kinds = {}
        for ev in reqs:
            key = f"{ev.get('model', '?')}:{ev.get('trace_kind', '?')}"
            kinds[key] = kinds.get(key, 0) + 1
        lines.append(
            "Requests (request-scoped traces; "
            + ", ".join(f"{k_}: {n}" for k_, n in sorted(kinds.items()))
            + ")")
        lines.append(
            f"{'trace':<18} {'model':<12} {'status':<14} {'total':>9} "
            f"{'queue':>8} {'exec':>8} {'decode':>8} {'unattr':>8}")

        def ms(v):
            return "-" if v is None else f"{float(v):.2f}"

        for ev in slowest:
            comp = (ev.get("decomposition") or {}).get(
                "components_ms", {})
            unattr = (ev.get("decomposition") or {}).get(
                "unattributed_ms")
            lines.append(
                f"{str(ev.get('trace', '?'))[:16]:<18} "
                f"{str(ev.get('model', '?'))[:12]:<12} "
                f"{str(ev.get('status', '?'))[:14]:<14} "
                f"{ms(ev.get('total_ms')):>9} "
                f"{ms(comp.get('queue.wait')):>8} "
                f"{ms(comp.get('batch.exec')):>8} "
                f"{ms(comp.get('decode')):>8} "
                f"{ms(unattr):>8}")
        if padded:
            lines.append("")
            lines.append("Padding waste (rows padded to reach the "
                         "bucket — top requests)")
            for ev in padded:
                pad = (ev.get("decomposition") or {}).get("padding", {})
                lines.append(
                    f"  {str(ev.get('trace', '?'))[:16]:<18} "
                    f"model={ev.get('model', '?')} "
                    f"padded={ev.get('padded_rows')} "
                    f"bucket={pad.get('bucket')} "
                    f"fill={pad.get('fill')}")

    pages = kv_page_activity(doc)
    if pages:
        lines.append("")
        lines.append("Generation (paged KV cache, kv.page events)")
        lines.append(
            f"{'model':<14} {'admits':>7} {'hits':>6} {'frees':>6} "
            f"{'cow':>5} {'blk alloc':>10} {'blk shared':>11}")
        for name in sorted(pages):
            a = pages[name]
            lines.append(
                f"{name[:14]:<14} {a['alloc'] + a['hit']:>7} "
                f"{a['hit']:>6} {a['free']:>6} {a['cow']:>5} "
                f"{a['blocks_alloc']:>10} {a['blocks_shared']:>11}")

    verdict, num_summary, _locates = numerics_info(doc)
    if verdict is not None or num_summary is not None:
        lines.append("")
        lines.append("Numerics (check_numerics tier)")
        if verdict is not None:
            stat = verdict.get("stat") or {}
            first = verdict.get("first_bad_op")
            if first:
                lines.append(
                    f"  first non-finite output: {first} "
                    f"(var {verdict.get('var')!r}, step "
                    f"{verdict.get('step')}, "
                    f"{'replayed' if verdict.get('replayed') else 'in-step'})")
                lines.append(
                    f"    nonfinite={stat.get('nonfinite')} "
                    f"abs_max={stat.get('abs_max')} "
                    f"abs_mean={stat.get('abs_mean')} l2={stat.get('l2')}")
            else:
                lines.append(
                    f"  locate replay found no non-finite op output "
                    f"(step {verdict.get('step')}, "
                    f"{verdict.get('rows_checked')} rows checked)")
        if num_summary is not None:
            lines.append(
                f"  last summary: grad_norm={num_summary.get('grad_norm')} "
                f"grad_nonfinite={num_summary.get('grad_nonfinite')} "
                f"nonfinite_rows={num_summary.get('nonfinite_rows')} "
                f"groups={num_summary.get('groups')}")

    trips = watchdog_trips(doc)
    if trips:
        lines.append("")
        lines.append("Watchdog trips")
        for t in trips:
            lines.append(f"  [{t.get('trip')}] step {t.get('step')}: "
                         f"{t.get('detail')}")
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="summarize a unified chrome trace / jax trace dir")
    p.add_argument("trace", help="merged trace JSON (or a jax trace dir)")
    p.add_argument("--top", type=int, default=20,
                   help="rows in the top-op table")
    args = p.parse_args(argv)
    print(report(load_trace(args.trace), args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
