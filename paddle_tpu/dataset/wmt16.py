"""WMT16 En-De translation dataset (reference:
python/paddle/dataset/wmt16.py — BPE-tokenized parallel corpus with
get_dict + train/test/validation readers yielding (src_ids, trg_ids,
trg_next_ids); the transformer/machine-translation workload's data).

Offline fallback: a synthetic 'translation' task — the target is a
deterministic per-token mapping of the source plus a reversal flag — so
seq2seq models trained on it genuinely learn a transduction."""

from __future__ import annotations

import numpy as np

from . import common

_SRC_VOCAB = 1000
_TRG_VOCAB = 1000
BOS, EOS, UNK = 0, 1, 2


def get_dict(lang, dict_size, reverse=False, synthetic=True):
    """reference wmt16.get_dict: token<->id for 'en'/'de'."""
    size = min(dict_size, _SRC_VOCAB if lang == "en" else _TRG_VOCAB)
    d = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
    for i in range(3, size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic_pairs(seed, n_samples, src_dict_size, trg_dict_size):
    rng = np.random.RandomState(seed)
    for _ in range(n_samples):
        ln = int(rng.randint(4, 16))
        src = rng.randint(3, src_dict_size, ln)
        # deterministic transduction: affine token map (mod vocab-3)
        trg = 3 + (src * 7 + 3) % (trg_dict_size - 3)
        yield src.tolist(), trg.tolist()


def _reader(seed, n_samples, src_dict_size, trg_dict_size, synthetic):
    def reader():
        if not common.use_synthetic(synthetic):
            raise RuntimeError(
                "wmt16: real-corpus mode needs the tar at the dataset "
                "cache path (zero-egress image) — use synthetic=True")
        for src, trg in _synthetic_pairs(seed, n_samples, src_dict_size,
                                         trg_dict_size):
            src_ids = [BOS] + src + [EOS]
            trg_ids = [BOS] + trg
            trg_next = trg + [EOS]
            yield src_ids, trg_ids, trg_next
    return reader


def train(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
          src_lang="en", synthetic=True, n_samples=2000):
    """src_lang is accepted for reference-signature parity; the synthetic
    transduction is language-agnostic (ids only)."""
    return _reader(31, n_samples, src_dict_size, trg_dict_size, synthetic)


def test(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
         src_lang="en", synthetic=True, n_samples=200):
    return _reader(32, n_samples, src_dict_size, trg_dict_size, synthetic)


def validation(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
               src_lang="en", synthetic=True, n_samples=200):
    return _reader(33, n_samples, src_dict_size, trg_dict_size, synthetic)
