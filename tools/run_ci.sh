#!/usr/bin/env bash
# CI entry (reference role: paddle/scripts/paddle_build.sh — cmake_gen:58,
# run_test:408).  Runs the full validation ladder on a plain CPU host:
#   1. lint/format gate (ruff or pyflakes when available, else a
#      compile-all syntax sweep — the gate must exist on a bare image)
#   2. full test suite on the virtual 8-device CPU mesh
#   3. bench smoke (real chip if present, else CPU) with telemetry,
#      flight recorder, and metrics-snapshot artifacts
#   4. compile-check + multichip dryrun (the driver's graft contract)
# Usage: tools/run_ci.sh [fast]   — "fast" skips the bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/4] lint gate"
if command -v ruff >/dev/null 2>&1; then
  ruff check paddle_tpu tools bench.py __graft_entry__.py
elif python -c 'import pyflakes' >/dev/null 2>&1; then
  python -m pyflakes paddle_tpu tools bench.py __graft_entry__.py
else
  echo "-- no ruff/pyflakes in image; falling back to compileall"
  python -m compileall -q paddle_tpu tools bench.py __graft_entry__.py
fi

echo "== [2/4] test suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

if [[ "${1:-}" != "fast" ]]; then
  echo "== [3/4] bench smoke (telemetry on; snapshot + flight artifacts)"
  mkdir -p ci_artifacts
  rm -f ci_artifacts/bench_steps.jsonl  # StepMonitor appends; keep one run
  rm -rf ci_artifacts/flight && mkdir -p ci_artifacts/flight
  FLAGS_monitor=1 FLAGS_monitor_jsonl=ci_artifacts/bench_steps.jsonl \
    FLAGS_flight_dir=ci_artifacts/flight \
    python bench.py --smoke --monitor-snapshot ci_artifacts/metrics.prom
  echo "-- metrics snapshot:"
  head -40 ci_artifacts/metrics.prom || true
  echo "-- flight record (black box of the smoke run):"
  ls ci_artifacts/flight/
  head -3 ci_artifacts/flight/flight-*-atexit.jsonl || true
fi

echo "== [4/4] entry compile-check + multichip dryrun"
python __graft_entry__.py

echo "CI OK"
