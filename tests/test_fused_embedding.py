"""Fused multi-table embedding tier (PERF.md round 8): the Pallas
gather/scatter-add/sparse-apply kernels (kernels/embedding.py), the
fused_lookup_table / fused_sparse_{sgd,adam} ops, the `fused_embedding`
graph pass, the dispatch-census collapse, and the pipelined CTR ingest.

The aliasing case most likely to break a fused gather/modify/scatter
pipeline is DUPLICATE ids within a batch — every trajectory test below
plants duplicates (within slots and across steps) and asserts parity
against the per-slot SelectedRows composition the reference semantics
define (lookup_table_op.h:132, selected_rows_functor.h MergeAdd,
adam_op.h lazy mode)."""

import contextlib
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, passes
from paddle_tpu.flags import FLAGS
from paddle_tpu.kernels import embedding as EK


@contextlib.contextmanager
def _fused(flag: bool):
    FLAGS.fused_embedding = bool(flag)
    try:
        yield
    finally:
        FLAGS.reset("fused_embedding")


def _hlo_diag():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "hlo_diag.py")
    spec = importlib.util.spec_from_file_location("_hlo_diag_sparse", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# kernel tier
# ---------------------------------------------------------------------------


class TestKernels:
    S, V, D, B = 5, 37, 10, 23  # awkward sizes: partial blocks, D < lane

    def _group(self, seed=0):
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        tables = [jnp.asarray(rng.rand(self.V, self.D), jnp.float32)
                  for _ in range(self.S)]
        ids = jnp.asarray(rng.randint(0, self.V, (self.S, self.B)), jnp.int32)
        ids = ids.at[:, 5].set(ids[:, 3]).at[:, 9].set(ids[:, 3])  # dups
        rows = jnp.asarray(rng.rand(self.S, self.B, self.D), jnp.float32)
        return tables, ids, rows

    def test_gather_matches_per_table(self):
        tables, ids, _ = self._group()
        out = EK.multi_table_gather(tables, ids, block_rows=8)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(EK.multi_table_gather_xla(tables, ids)))

    def test_merge_matches_selected_rows_merged(self):
        """Batched MergeAdd == per-slot SelectedRows.merged(), duplicate
        ids included (same uids, same summed rows, same sentinel tail)."""
        from paddle_tpu.core.selected_rows import SelectedRows

        tables, ids, rows = self._group()
        uids, mrows = EK.merge_slot_rows(ids, rows, self.V)
        for s in range(self.S):
            u_ref, m_ref = SelectedRows(ids[s], rows[s], self.V).merged()
            np.testing.assert_array_equal(np.asarray(uids[s]),
                                          np.asarray(u_ref))
            np.testing.assert_allclose(np.asarray(mrows[s]),
                                       np.asarray(m_ref), atol=1e-6)

    def test_scatter_add_duplicates_exact(self):
        """Fused scatter-add == numpy add.at accumulation (duplicates
        merged first; sentinel tail rows are dropped)."""
        import jax.numpy as jnp

        tables, ids, rows = self._group()
        uids, mrows = EK.merge_slot_rows(ids, rows, self.V)
        # interpret=True: exercise the aliased DMA kernel itself on the
        # CPU box (the interpret=None default takes the XLA apply off-TPU)
        got = EK.multi_table_scatter_add(tables, uids, mrows,
                                         jnp.float32(1.0), block_rows=8,
                                         interpret=True)
        for s in range(self.S):
            ref = np.asarray(tables[s]).copy()
            np.add.at(ref, np.asarray(ids[s]), np.asarray(rows[s]))
            np.testing.assert_allclose(np.asarray(got[s]), ref, atol=1e-5)

    def test_sparse_adam_matches_reference(self):
        import jax.numpy as jnp

        tables, ids, rows = self._group()
        rng = np.random.RandomState(3)
        m1s = [jnp.asarray(rng.rand(self.V, self.D), jnp.float32)
               for _ in range(self.S)]
        m2s = [jnp.asarray(rng.rand(self.V, self.D), jnp.float32)
               for _ in range(self.S)]
        uids, mrows = EK.merge_slot_rows(ids, rows, self.V)
        args = (uids, mrows, jnp.float32(0.01), 0.9, 0.999, 1e-8)
        po, m1o, m2o = EK.multi_table_sparse_adam(
            tables, m1s, m2s, *args, block_rows=8, interpret=True)
        pr, m1r, m2r = EK.multi_table_sparse_adam_xla(
            tables, m1s, m2s, *args)
        for got, ref in ((po, pr), (m1o, m1r), (m2o, m2r)):
            for g, r in zip(got, ref):
                np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                           atol=1e-6)

    def test_non_float_group_falls_back_to_xla(self):
        """Off-contract groups must take the per-table composition, not
        crash in the kernel."""
        import jax.numpy as jnp

        tables = [jnp.arange(20, dtype=jnp.int32).reshape(10, 2)
                  for _ in range(2)]
        ids = jnp.asarray([[1, 2, 1], [0, 9, 9]], jnp.int32)
        out = EK.multi_table_gather(tables, ids)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(EK.multi_table_gather_xla(tables, ids)))


# ---------------------------------------------------------------------------
# pass + op tier (mini group: fast compiles)
# ---------------------------------------------------------------------------

SLOTS, VOCAB, DIM = 4, 53, 8


def _build_mini(optimizer="adam", is_sparse=True, fused=False):
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            slots = [layers.data(name=f"s{i}", shape=[1], dtype="int64")
                     for i in range(SLOTS)]
            y = layers.data(name="y", shape=[1], dtype="int64")
            embs = [
                layers.embedding(s, size=[VOCAB, DIM], is_sparse=is_sparse,
                                 param_attr=pt.ParamAttr(name=f"tbl_{i}"))
                for i, s in enumerate(slots)
            ]
            h = layers.concat(embs, axis=1)
            logits = layers.fc(h, size=2)
            loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
            if optimizer == "adam":
                pt.optimizer.Adam(learning_rate=0.05,
                                  lazy_mode=True).minimize(loss)
            elif optimizer == "adam_nonlazy":
                pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
            else:
                pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    if fused:
        passes.apply_pass("fused_embedding", prog)
    prog.random_seed = 7
    return prog, startup, loss


def _mini_batch(bs=32, seed=0, dup=True):
    rng = np.random.RandomState(seed)
    feed = {f"s{i}": rng.randint(0, VOCAB, (bs, 1)).astype("int64")
            for i in range(SLOTS)}
    if dup:
        for i in range(SLOTS):
            feed[f"s{i}"][bs // 2:] = feed[f"s{i}"][:bs - bs // 2]
    feed["y"] = rng.randint(0, 2, (bs, 1)).astype("int64")
    return feed


def _train(prog, startup, loss, batches, fetch_extra=()):
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for b in batches:
        outs = exe.run(prog, feed=b, fetch_list=[loss, *fetch_extra],
                       scope=scope)
        losses.append(float(np.asarray(outs[0])))
    return losses, scope


def _ops(prog):
    return [op.type for op in prog.global_block().ops]


class TestPass:
    def test_census_mini(self):
        prog, _, _ = _build_mini(fused=True)
        ops = _ops(prog)
        assert ops.count("fused_lookup_table") == 1
        assert ops.count("fused_lookup_table_grad") == 1
        assert ops.count("fused_sparse_adam") == 1
        assert "lookup_table" not in ops
        assert "lookup_table_grad" not in ops
        # the 4 per-table adam chains collapsed; only the fc ones remain
        assert ops.count("adam") == 2  # fc w + b

    def test_census_deepfm(self):
        """The flagship CTR net: 2x26 lookups -> 2 fused groups, the 52
        per-table lazy-adam chains -> 2 group applies (graph-level launch
        collapse, program build only — no compile)."""
        from paddle_tpu.models import deepfm as D

        with _fused(True):
            prog, _ = pt.Program(), pt.Program()
            with pt.program_guard(prog, pt.Program()):
                with pt.core.framework.guard_unique_name():
                    D.build_train_net(hash_dim=101, embedding_size=4)
        ops = _ops(prog)
        assert ops.count("fused_lookup_table") == 2
        assert ops.count("fused_lookup_table_grad") == 2
        assert ops.count("fused_sparse_adam") == 2
        assert "lookup_table" not in ops

    def test_flag_off_graph_identical_to_per_slot(self):
        """FLAGS_fused_embedding off => the model builder emits the exact
        per-slot composition (no fused op anywhere), with the same
        parameter set as the fused build (checkpoint interop)."""
        from paddle_tpu.models import deepfm as D

        progs = {}
        for flag in (True, False):
            with _fused(flag):
                prog = pt.Program()
                with pt.program_guard(prog, pt.Program()):
                    with pt.core.framework.guard_unique_name():
                        D.build_train_net(hash_dim=101, embedding_size=4)
                progs[flag] = prog
        ops_off = _ops(progs[False])
        assert not any(t.startswith("fused_") for t in ops_off)
        assert ops_off.count("lookup_table") == 52
        params = {
            flag: sorted(p.name
                         for p in progs[flag].global_block().all_parameters())
            for flag in progs
        }
        assert params[True] == params[False]

    def test_pass_skips_shared_table(self):
        """Two lookups through ONE table (grad accumulation via sum)
        must not coalesce — the fused grad contract is one table per
        slot."""
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                a = layers.data(name="a", shape=[1], dtype="int64")
                b = layers.data(name="b", shape=[1], dtype="int64")
                e1 = layers.embedding(a, size=[VOCAB, DIM], is_sparse=True,
                                      param_attr=pt.ParamAttr(name="shared"))
                e2 = layers.embedding(b, size=[VOCAB, DIM], is_sparse=True,
                                      param_attr=pt.ParamAttr(name="shared"))
                layers.mean(layers.concat([e1, e2], axis=1))
        assert passes.apply_pass("fused_embedding", prog) == 0
        assert "fused_lookup_table" not in _ops(prog)

    def test_pass_skips_non_lazy_adam_optimizer_tier(self):
        """Non-lazy adam densifies per table — the lookup/grad tiers fuse
        but the optimizer ops stay per-table."""
        prog, _, _ = _build_mini(optimizer="adam_nonlazy", fused=True)
        ops = _ops(prog)
        assert ops.count("fused_lookup_table") == 1
        assert "fused_sparse_adam" not in ops
        assert ops.count("adam") == SLOTS + 2

    def test_layers_fused_embedding_helper(self):
        """The direct-build route: layers.fused_embedding emits the op,
        backward flows through the fused grad maker, training learns."""
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                slots = [layers.data(name=f"s{i}", shape=[1], dtype="int64")
                         for i in range(SLOTS)]
                y = layers.data(name="y", shape=[1], dtype="int64")
                embs = layers.fused_embedding(
                    slots, size=[VOCAB, DIM], is_sparse=True,
                    param_attrs=[pt.ParamAttr(name=f"tbl_{i}")
                                 for i in range(SLOTS)])
                h = layers.concat(embs, axis=1)
                loss = layers.mean(layers.softmax_with_cross_entropy(
                    layers.fc(h, size=2), y))
                pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        ops = _ops(prog)
        assert ops.count("fused_lookup_table") == 1
        assert ops.count("fused_lookup_table_grad") == 1
        prog.random_seed = 7
        losses, _ = _train(prog, startup, loss,
                           [_mini_batch()] * 6)
        assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# trajectory parity: fused vs per-slot (the acceptance A/B)
# ---------------------------------------------------------------------------


class TestTrajectoryParity:
    def _run_mini(self, fused, optimizer, is_sparse=True, steps=6):
        prog, startup, loss = _build_mini(optimizer=optimizer,
                                          is_sparse=is_sparse, fused=fused)
        batches = [_mini_batch(seed=s) for s in range(steps)]
        losses, scope = _train(prog, startup, loss, batches)
        tables = {f"tbl_{i}": np.asarray(scope.find_var(f"tbl_{i}"))
                  for i in range(SLOTS)}
        moments = {
            n: np.asarray(scope.find_var(n))
            for n in scope.local_var_names()
            if "moment" in n and scope.find_var(n) is not None
        }
        return losses, tables, moments

    @pytest.mark.parametrize("optimizer", ["sgd", "adam"])
    def test_mini_parity_duplicate_ids(self, optimizer):
        """Fused vs per-slot trajectories on duplicate-heavy batches:
        losses, final tables AND (lazy-adam) row-sparse moments match —
        the SelectedRows duplicate-row merge + lazy moment semantics of
        the reference survive the fusion."""
        lf, tf, mf = self._run_mini(True, optimizer)
        lu, tu, mu = self._run_mini(False, optimizer)
        np.testing.assert_allclose(lf, lu, rtol=2e-4, atol=2e-5)
        for n in tf:
            np.testing.assert_allclose(tf[n], tu[n], rtol=2e-4, atol=2e-5)
        assert set(mf) == set(mu)
        for n in mf:
            np.testing.assert_allclose(mf[n], mu[n], rtol=2e-4, atol=2e-5)

    def test_mini_parity_dense_grads(self):
        """is_sparse=False: the fused backward runs the multi-table
        scatter-add kernel into dense grads — trajectories must still
        match the per-slot dense composition."""
        lf, tf, _ = self._run_mini(True, "sgd", is_sparse=False)
        lu, tu, _ = self._run_mini(False, "sgd", is_sparse=False)
        np.testing.assert_allclose(lf, lu, rtol=2e-4, atol=2e-5)
        for n in tf:
            np.testing.assert_allclose(tf[n], tu[n], rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_deepfm_train_step_parity(self):
        """The acceptance A/B on the real DeepFM train step (26 slots,
        both table groups, lazy adam), duplicate-ids batch included."""
        from paddle_tpu.models import deepfm as D

        results = {}
        for flag in (True, False):
            with _fused(flag):
                prog, startup = pt.Program(), pt.Program()
                with pt.program_guard(prog, startup):
                    with pt.core.framework.guard_unique_name():
                        avg, _, _, _ = D.build_train_net(
                            hash_dim=101, embedding_size=4)
                prog.random_seed = 7
                scope = pt.Scope()
                exe = pt.Executor(pt.CPUPlace())
                exe.run(startup, scope=scope)
                batch = D.make_batch(32, hash_dim=101,
                                     rng=np.random.RandomState(0))
                for i in range(26):  # plant within-batch duplicates
                    batch[f"C{i}"][5:10] = batch[f"C{i}"][0]
                losses = []
                for _ in range(5):
                    (l,) = exe.run(prog, feed=batch, fetch_list=[avg],
                                   scope=scope)
                    losses.append(float(np.asarray(l)))
                results[flag] = (losses,
                                 np.asarray(scope.find_var("deepfm_emb_3")))
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=2e-4, atol=2e-5)
        assert results[True][0][-1] < results[True][0][0]

    def test_checkpoint_interop_across_flag(self):
        """Params trained on the fused path load into a flag-off program
        (same names/shapes) and produce the identical next step."""
        prog_f, startup_f, loss_f = _build_mini(fused=True)
        batches = [_mini_batch(seed=s) for s in range(3)]
        _, scope_f = _train(prog_f, startup_f, loss_f, batches)

        prog_u, startup_u, loss_u = _build_mini(fused=False)
        scope_u = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup_u, scope=scope_u)
        for n in scope_u.local_var_names():
            v = scope_f.find_var(n)
            if v is not None:
                # materialized copy: the flag-off run donates its buffers,
                # which must not delete the fused scope's arrays
                scope_u.set_var(n, np.array(np.asarray(v)))
        nxt = _mini_batch(seed=9)
        (lu,) = exe.run(prog_u, feed=nxt, fetch_list=[loss_u], scope=scope_u)
        exe_f = pt.Executor(pt.CPUPlace())
        (lf,) = exe_f.run(prog_f, feed=nxt, fetch_list=[loss_f],
                          scope=scope_f)
        np.testing.assert_allclose(float(np.asarray(lf)),
                                   float(np.asarray(lu)),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# dispatch census + convert hoist (tools/hlo_diag.py --sparse)
# ---------------------------------------------------------------------------


class TestSparseCensus:
    def _lower(self, fused):
        import jax

        prog, startup, loss = _build_mini(fused=fused)
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        # run_steps keeps the jitted handle for AOT lowering
        # (tools/hlo_diag.py lower_entry idiom)
        feed = {k: v[None] for k, v in _mini_batch().items()}
        exe.run_steps(prog, feed=feed, fetch_list=[loss], scope=scope)
        from paddle_tpu.core.executor import latest_jitted_entry

        entry = latest_jitted_entry(exe)
        rw = [scope.find_var(n) for n in entry.rw_state]
        ro = [scope.find_var(n) for n in entry.ro_state]
        feed_names = sorted(feed)
        feed_vals = [exe._to_device_array(prog, n, feed[n])
                     for n in feed_names]
        lowered = entry.jitted.lower(feed_vals, rw, ro,
                                     jax.random.PRNGKey(0))
        return lowered.compile().as_text(), prog

    def test_fused_census_collapse_and_convert_hoist(self):
        """Satellites 1+2: the fused step's HLO drops the per-slot gather
        tier (one launch per group) and the per-slot int64->int32
        converts (one hoisted cast on the stacked ids)."""
        hd = _hlo_diag()
        txt_f, prog_f = self._lower(True)
        txt_u, prog_u = self._lower(False)
        rep_f = hd.analyze_sparse(txt_f, prog_f)
        rep_u = hd.analyze_sparse(txt_u, prog_u)
        # graph-level launch collapse: one fused gather for all slots
        assert rep_u["graph"]["gather_launches"] == SLOTS
        assert rep_f["graph"]["gather_launches"] == 1
        assert rep_f["graph"]["optimizer_launches"] \
            < rep_u["graph"]["optimizer_launches"]
        # HLO-level: the per-slot embedding gathers are gone (residual
        # gathers belong to the loss, not the lookup tier)
        assert rep_f["hlo_gather"] <= rep_u["hlo_gather"] - (SLOTS - 1)
        # convert hoist: per-slot casts collapse to the one stacked cast
        assert rep_f["hlo_convert"] < rep_u["hlo_convert"]

    def test_deepfm_graph_launch_targets(self):
        """The acceptance numbers on the full CTR net (graph level, no
        compile): ONE gather launch per 26-slot table group and >= 10x
        fewer sparse optimizer applies."""
        from paddle_tpu.models import deepfm as D

        counts = {}
        for flag in (True, False):
            with _fused(flag):
                prog = pt.Program()
                with pt.program_guard(prog, pt.Program()):
                    with pt.core.framework.guard_unique_name():
                        D.build_train_net(hash_dim=101, embedding_size=4)
            ops = _ops(prog)
            counts[flag] = ops
        assert counts[True].count("fused_lookup_table") == 2
        assert counts[True].count("lookup_table") == 0
        sparse_applies_unfused = counts[False].count("adam") - 8  # fc tier
        sparse_applies_fused = counts[True].count("fused_sparse_adam")
        assert sparse_applies_unfused == 52
        assert sparse_applies_fused == 2
        assert sparse_applies_unfused / sparse_applies_fused >= 10


# ---------------------------------------------------------------------------
# monitor gauges (satellite 6) + pipelined ingest
# ---------------------------------------------------------------------------


class TestTelemetry:
    def test_embedding_gauges_set_per_traced_step(self):
        import paddle_tpu.monitor as monitor

        monitor.default_registry().reset()
        FLAGS.monitor = True
        try:
            prog, startup, loss = _build_mini(fused=True)
            _train(prog, startup, loss, [_mini_batch()])
            reg = monitor.default_registry()
            g = reg.get("embedding.gather_launches")
            rows = reg.get("embedding.sparse_rows_touched")
            assert g is not None and g.value == 1
            assert rows is not None and rows.value == SLOTS * 32
        finally:
            FLAGS.reset("monitor")
            monitor.default_registry().reset()

    def test_embedding_gauges_zero_cost_off(self):
        import paddle_tpu.monitor as monitor

        monitor.default_registry().reset()
        prog, startup, loss = _build_mini(fused=True)
        _train(prog, startup, loss, [_mini_batch()])
        assert monitor.default_registry().get(
            "embedding.gather_launches") is None

    def test_per_slot_path_counts_every_launch(self):
        import paddle_tpu.monitor as monitor

        monitor.default_registry().reset()
        FLAGS.monitor = True
        try:
            prog, startup, loss = _build_mini(fused=False)
            _train(prog, startup, loss, [_mini_batch()])
            g = monitor.default_registry().get("embedding.gather_launches")
            assert g is not None and g.value == SLOTS
        finally:
            FLAGS.reset("monitor")
            monitor.default_registry().reset()


class TestPipelinedIngest:
    def _files(self, tmp_path, n_files=2, lines=24):
        rng = np.random.RandomState(5)
        files = []
        for fi in range(n_files):
            path = tmp_path / f"part-{fi}.txt"
            with open(path, "w") as f:
                for _ in range(lines):
                    ids = rng.randint(0, VOCAB, 3)
                    label = float(ids[0] % 2)
                    f.write("3 " + " ".join(map(str, ids))
                            + f" 1 {label}\n")
            files.append(str(path))
        return files

    def _net(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                ids = layers.data(name="ids", shape=[8], dtype="int64")
                label = layers.data(name="label", shape=[1],
                                    dtype="float32")
                emb = layers.embedding(
                    layers.reshape(ids, [-1, 8, 1]), size=[VOCAB, DIM])
                pooled = layers.reduce_sum(emb, dim=1)
                logit = layers.fc(pooled, size=1)
                loss = layers.mean(
                    layers.sigmoid_cross_entropy_with_logits(logit, label))
                pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
        prog.random_seed = 3
        return prog, startup, loss

    def _desc(self):
        desc = pt.DataFeedDesc(batch_size=8, name="ctr")
        desc.add_slot("ids", type="uint64", max_len=8, id_space=VOCAB)
        desc.add_slot("label", type="float", is_dense=True, dim=1)
        return desc

    def test_pipelined_matches_strict_loop(self, tmp_path):
        """Double-buffered ingest returns the identical per-batch fetches
        (same batches, same order, same values) as the strict
        parse->put->run->sync loop."""
        files = self._files(tmp_path)
        results = {}
        for pipeline in (False, True):
            prog, startup, loss = self._net()
            scope = pt.Scope()
            aexe = pt.AsyncExecutor(pt.CPUPlace())
            aexe.executor.run(startup, scope=scope)
            res = aexe.run_from_files(
                prog, self._desc(), files, thread_num=1,
                fetch_list=[loss], scope=scope, pipeline=pipeline)
            results[pipeline] = [r[0] for r in res]
        assert len(results[True]) == len(results[False]) > 0
        np.testing.assert_allclose(results[True], results[False],
                                   rtol=1e-6, atol=1e-7)

    def test_pipelined_ingest_telemetry(self, tmp_path):
        import paddle_tpu.monitor as monitor

        monitor.default_registry().reset()
        FLAGS.monitor = True
        try:
            files = self._files(tmp_path)
            prog, startup, loss = self._net()
            scope = pt.Scope()
            aexe = pt.AsyncExecutor(pt.CPUPlace())
            aexe.executor.run(startup, scope=scope)
            aexe.run_from_files(prog, self._desc(), files, thread_num=1,
                                fetch_list=[loss], scope=scope,
                                pipeline=True)
            reg = monitor.default_registry()
            assert reg.get("data_feed.pipelined_batches").value > 0
            assert reg.get("data_feed.inflight_steps").value == 0  # drained
            assert reg.get("data_feed.batches").value > 0
        finally:
            FLAGS.reset("monitor")
            monitor.default_registry().reset()
