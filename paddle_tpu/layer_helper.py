"""LayerHelper: shared machinery for layer functions
(reference: python/paddle/fluid/layer_helper.py:55,289)."""

from __future__ import annotations

from .core import framework as fw
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else fw.unique_name(layer_type)

    @property
    def main_program(self) -> fw.Program:
        return fw.default_main_program()

    @property
    def startup_program(self) -> fw.Program:
        return fw.default_startup_program()

    # -- params -----------------------------------------------------------
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = fw.unique_name(".".join([self.name, "b" if is_bias else "w"]))
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()

        main_block = self.main_program.global_block()
        # Dygraph: a named parameter that already holds an eager value is
        # REUSED, not re-initialized — otherwise every layers.* call in a
        # training loop would reset the weights it just trained (the
        # reference's dygraph layers hold params across forward calls).
        from . import imperative as _imp

        if _imp.enabled() and attr.name in _imp._session.values:
            existing = main_block._find_var_recursive(attr.name)
            if existing is not None:
                return existing
        param = main_block.create_parameter(
            attr.name,
            shape,
            dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
            do_model_average=attr.do_model_average,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        # mirrored param in startup program with its init op
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            attr.name, shape, dtype, trainable=attr.trainable
        )
        init(sp, startup_block)
        return param

    # -- vars -------------------------------------------------------------
    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=fw.unique_name(".".join([self.name, "tmp"])),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, stop_gradient=True, **kwargs
        )

    def set_variable_initializer(self, var, initializer):
        """Create the same var in the startup program and init it there."""
        sb = self.startup_program.global_block()
        sv = sb.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype, persistable=True
        )
        initializer(sv, sb)
        return var

    # -- ops --------------------------------------------------------------
    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def input_dtype(self, input_param_name="input"):
        x = self.kwargs.get(input_param_name)
        if isinstance(x, (list, tuple)):
            x = x[0]
        return x.dtype

    # -- bias/activation epilogues ---------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr()
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            "elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]}, attrs=act)
        return tmp
