"""Imperative (dygraph) mode: define-by-run eager execution with autograd
(reference: paddle/fluid/imperative/ — VarBase/OpBase layer.h:99,
Tracer::Trace tracer.cc:42, Autograd walk layer.cc; python
fluid/imperative/ base.py to_variable, layers.py Layer).

TPU-first design: under `imperative.guard()` every op appended through the
layers DSL ALSO executes immediately through its registered JAX lowering
(the same single source of truth the compiled executor traces), recording a
tape.  `.backward()` walks the tape in reverse, computing per-op input
cotangents with jax.vjp of the op's lowering — the eager twin of the
compiled path's generic vjp grad maker.  Because ops execute as plain JAX
calls, eager work still runs on the TPU (dispatched op-by-op rather than
as one fused XLA program).

Python control flow IS the dygraph control flow; program-level while/cond
sub-blocks are rejected in eager mode (the reference's dygraph had no
control-flow ops either at Fluid 1.2)."""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..core import framework as fw
from ..core import registry

_session: Optional["EagerSession"] = None


def enabled() -> bool:
    return _session is not None


def _require_session() -> "EagerSession":
    if _session is None:
        raise RuntimeError(
            "imperative API used outside paddle_tpu.imperative.guard()")
    return _session


class EagerSession:
    """Value store + tape + PRNG state for one guard scope (the eager
    counterpart of TraceContext + Scope)."""

    def __init__(self, seed=0):
        import jax

        self.values: Dict[str, object] = {}
        self.tape: List[tuple] = []  # (opdef, op, ctx, input-value snapshot)
        self.grads: Dict[str, object] = {}
        self.is_test = False
        self.mesh = None
        self.amp_bf16 = False
        self._base_key = jax.random.PRNGKey(seed)
        self._rng_counter = 0
        self._op_keys: Dict[int, object] = {}

    def next_rng_key(self, op=None):
        import jax

        # fixed per-op key so the backward vjp re-execution sees the SAME
        # randomness the forward drew (dropout masks etc.)
        if op is not None and id(op) in self._op_keys:
            return self._op_keys[id(op)]
        self._rng_counter += 1
        key = jax.random.fold_in(self._base_key, self._rng_counter)
        if op is not None:
            self._op_keys[id(op)] = key
        return key


def _run_op(session: EagerSession, block, op):
    import jax.numpy as jnp

    if op.attrs.get("sub_block") is not None:
        raise NotImplementedError(
            f"imperative mode: op {op.type!r} with a sub-block is not "
            "supported — use Python control flow in dygraph")
    opdef = registry.lookup(op.type)
    if opdef is None:
        raise RuntimeError(f"no lowering registered for op {op.type!r}")
    from ..flags import FLAGS

    if FLAGS.record_lowered_ops:
        # eager twin of the executor-trace hook: ops exercised only in
        # dygraph still count toward the op-contract executed set
        from ..monitor import flight as _flight

        _flight.note_lowered_ops([op.type])

    ins = {
        slot: [session.values.get(n) if n else None for n in names]
        for slot, names in op.inputs.items()
    }
    ctx = registry.LowerContext(op, op.attrs, session)
    outs = opdef.lower(ctx, ins)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if n and i < len(vals):
                session.values[n] = vals[i]
    if not opdef.no_grad:
        # snapshot the input VALUES at forward time: ops that write back to
        # an input name (batch_norm running stats) or any later name reuse
        # must not change what the backward vjp re-executes against
        session.tape.append((opdef, op, ctx, ins))


def _eager_hook(block, op):
    _run_op(_require_session(), block, op)


@contextlib.contextmanager
def guard(seed=0):
    """Enter dygraph mode (reference: fluid.imperative.guard()).  Fresh
    default programs + unique names; every layers.* call executes
    immediately."""
    global _session
    if _session is not None:
        raise RuntimeError("imperative.guard() does not nest")
    old_main = fw.switch_main_program(fw.Program())
    old_startup = fw.switch_startup_program(fw.Program())
    _session = EagerSession(seed=seed)
    fw._eager_op_hook = _eager_hook
    try:
        with fw.guard_unique_name():
            yield
    finally:
        fw._eager_op_hook = None
        _session = None
        fw.switch_main_program(old_main)
        fw.switch_startup_program(old_startup)


def to_variable(value, name=None, stop_gradient=False):
    """numpy -> eager Variable (reference imperative/base.py to_variable)."""
    import jax.numpy as jnp

    session = _require_session()
    arr = np.asarray(value)
    block = fw.default_main_program().current_block()
    var = block.create_var(
        name=name or fw.unique_name("eager_tmp"),
        shape=list(arr.shape),
        dtype=str(arr.dtype),
    )
    var.stop_gradient = stop_gradient
    session.values[var.name] = jnp.asarray(arr)
    return var


def _accumulate(d, name, g):
    if name in d:
        d[name] = d[name] + g
    else:
        d[name] = g


def backward(loss_var):
    """Autograd walk over the tape (reference imperative Autograd,
    layer.cc): seeds d(loss)=1 and pushes cotangents through each recorded
    op via jax.vjp of its lowering."""
    import jax
    import jax.numpy as jnp

    session = _require_session()
    loss_val = session.values[loss_var.name]
    if np.prod(loss_val.shape) != 1:
        raise ValueError("backward() needs a scalar loss")
    session.grads = {loss_var.name: jnp.ones_like(loss_val)}
    grads = session.grads

    for opdef, op, ctx, in_struct in reversed(session.tape):
        out_slots = {
            slot: [n for n in names]
            for slot, names in op.outputs.items()
        }
        # skip ops that contributed nothing to the loss
        if not any(
            n in grads for names in out_slots.values() for n in names if n
        ):
            continue

        def fwd(diff_ins):
            merged = {
                slot: [
                    (diff_ins[slot][i]
                     if diff_ins.get(slot) and diff_ins[slot][i] is not None
                     else in_struct[slot][i])
                    for i in range(len(in_struct[slot]))
                ]
                for slot in in_struct
            }
            return opdef.lower(ctx, merged)

        # differentiate only inexact-float inputs
        diff_ins = {
            slot: [
                v if (v is not None and hasattr(v, "dtype")
                      and jnp.issubdtype(v.dtype, jnp.inexact))
                else None
                for v in vals
            ]
            for slot, vals in in_struct.items()
        }
        out_vals, vjp_fn = jax.vjp(fwd, diff_ins)
        cots = {
            slot: [
                (grads.get(n) if n and n in grads
                 else (jnp.zeros_like(v) if v is not None else None))
                for n, v in zip(out_slots.get(slot, []), vals)
            ]
            for slot, vals in out_vals.items()
        }
        (in_cots,) = vjp_fn(cots)
        for slot, names in op.inputs.items():
            for n, g in zip(names, in_cots.get(slot, [])):
                if n and g is not None and hasattr(g, "dtype") \
                        and jnp.issubdtype(g.dtype, jnp.inexact):
                    var = fw.default_main_program().current_block(
                    )._find_var_recursive(n)
                    if var is not None and getattr(var, "stop_gradient",
                                                   False):
                        continue
                    _accumulate(grads, n, g)


class Layer:
    """Dygraph layer base (reference: python fluid/imperative/layers.py).
    Subclass and implement forward(); parameters() returns this layer's
    own tracked parameters plus those of sub-Layers found on attributes."""

    def __init__(self, name_scope=None):
        self._name_scope = name_scope
        self._own_params: List[fw.Variable] = []

    def _track(self, *params):
        for p in params:
            if p is not None:
                self._own_params.append(p)

    def __call__(self, *args, **kwargs):
        # adopt parameters created DURING forward (functional layers.*
        # calls create their weights on first use; without adoption a
        # layer mixing build-once sub-Layers with functional calls would
        # silently drop the functional weights from parameters()).  Every
        # nesting level adopts what appeared during ITS forward — so
        # sub.parameters() works too — but only the appended tail is
        # diffed (all_parameters() is creation-ordered), so steady-state
        # cost after the first call is O(P) list construction, no set
        # building.
        before_len = len(fw.default_main_program().all_parameters())
        out = self.forward(*args, **kwargs)
        created = fw.default_main_program().all_parameters()[before_len:]
        for p in created:
            if all(p is not q for q in self._own_params):
                self._track(p)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def sublayers(self):
        subs = []
        for v in vars(self).values():
            if isinstance(v, Layer):
                subs.append(v)
            elif isinstance(v, (list, tuple)):
                subs.extend(x for x in v if isinstance(x, Layer))
        return subs

    def _tracked_parameters(self):
        params = list(getattr(self, "_own_params", []))
        for sub in self.sublayers():
            params.extend(sub._tracked_parameters())
        return params

    def parameters(self):
        # dedup by name: a lazily-built sub-Layer weight is tracked by the
        # sub-Layer AND adopted by the enclosing __call__.  A never-called
        # or stateless layer correctly reports [] (no whole-program
        # fallback: parameter_list=sub.parameters() must never leak other
        # modules' weights).
        seen, params = set(), []
        for p in self._tracked_parameters():
            if p.name not in seen:
                seen.add(p.name)
                params.append(p)
        return params

    def clear_gradients(self):
        clear_gradients()


def parameters():
    """All eager parameters created so far in this guard scope."""
    return list(fw.default_main_program().all_parameters())


def value_of(var) -> np.ndarray:
    return np.asarray(_require_session().values[var.name])


def gradient_of(var) -> Optional[np.ndarray]:
    g = _require_session().grads.get(var.name)
    return None if g is None else np.asarray(g)


def apply_sgd(lr: float):
    """Minimal eager optimizer step: p -= lr * grad for every parameter
    with a gradient (dygraph training loops in the reference era did the
    same through the optimizer's eager path)."""
    session = _require_session()
    for p in parameters():
        g = session.grads.get(p.name)
        if g is not None:
            session.values[p.name] = session.values[p.name] - lr * g


def clear_gradients():
    _require_session().grads = {}
    _require_session().tape.clear()


# -- Variable conveniences ---------------------------------------------------


def _var_numpy(self):
    return value_of(self)


def _var_gradient(self):
    return gradient_of(self)


def _var_backward(self):
    return backward(self)


fw.Variable.numpy = _var_numpy
fw.Variable.gradient = _var_gradient
fw.Variable.backward = _var_backward


# imported at the bottom: nn's Layer classes subclass Layer defined above
from . import nn  # noqa: E402,F401
