"""SelectedRows: the row-sparse tensor for embedding gradients.

Capability parity with the reference's SelectedRows
(reference: paddle/fluid/framework/selected_rows.h:32 — a {rows, value,
height} triple carrying only the touched rows of a tall tensor;
math/selected_rows_functor.h MergeAdd/SelectedRowsAddToTensor), redesigned
TPU-first:

  * XLA needs static shapes, so a SelectedRows here is a pytree of
    `ids [K] int32` + `rows [K, ...]` with K = the (static) number of
    lookups, duplicates allowed — no dynamic-size unique().
  * Deduplication (reference MergeAdd) is `merged()`: argsort + segment-sum
    at static size K, with out-of-range sentinel ids (= height) padding the
    unused tail.  JAX scatters DROP out-of-bounds indices and gathers CLIP
    them, which makes sentinel-padded updates exact no-ops.
  * The payoff: optimizer updates touch O(K·D) HBM instead of O(vocab·D) —
    scatter-add on a donated buffer updates the table in place.  This is
    what makes hash_dim=1e6 x 26-slot CTR training (dist_ctr.py) viable.

Registered as a jax pytree so it flows through jit/scan/vjp boundaries.
"""

from __future__ import annotations


class SelectedRows:
    """rows [K, ...] + ids [K] + height (static vocab size)."""

    __slots__ = ("ids", "rows", "height")

    def __init__(self, ids, rows, height: int):
        self.ids = ids
        self.rows = rows
        self.height = int(height)

    # -- array-like surface (lets amp cast policies treat it uniformly) ----
    @property
    def dtype(self):
        return self.rows.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.rows.shape[1:])

    def astype(self, dtype):
        if self.rows.dtype == dtype:
            return self
        return SelectedRows(self.ids, self.rows.astype(dtype), self.height)

    def __repr__(self):
        return (
            f"SelectedRows(ids={getattr(self.ids, 'shape', None)}, "
            f"rows={getattr(self.rows, 'shape', None)}, height={self.height})"
        )

    # -- reference-functor equivalents -------------------------------------
    def merged(self):
        """MergeAdd (selected_rows_functor.h): combine duplicate ids.

        Returns (uids [K], mrows [K, ...]) where each unique id appears once
        with its row-summed value; unused tail slots have uid == height
        (out of range — dropped by scatter, clipped by gather)."""
        import jax
        import jax.numpy as jnp

        ids = self.ids.reshape(-1).astype("int32")
        k = ids.shape[0]
        order = jnp.argsort(ids)
        sids = ids[order]
        srows = self.rows[order]
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), sids[1:] != sids[:-1]]
        )
        seg = jnp.cumsum(is_start.astype("int32")) - 1  # [K] in [0, K)
        mrows = jax.ops.segment_sum(srows, seg, num_segments=k)
        uids = jnp.full((k,), self.height, "int32").at[seg].set(sids)
        return uids, mrows

    def to_dense(self, like=None):
        """SelectedRowsAddToTensor: scatter-add into a dense zero tensor."""
        import jax.numpy as jnp

        if like is not None:
            base = jnp.zeros_like(like)
        else:
            base = jnp.zeros(self.shape, self.rows.dtype)
        ids = self.ids.reshape(-1).astype("int32")
        return base.at[ids].add(
            self.rows.astype(base.dtype), mode="drop"
        )

    def add_to(self, dense):
        """dense + this (used by the sum op for mixed dense/sparse)."""
        ids = self.ids.reshape(-1).astype("int32")
        return dense.at[ids].add(self.rows.astype(dense.dtype), mode="drop")

    @staticmethod
    def concat(items):
        """Sum of SelectedRows = concatenation (duplicates are fine)."""
        import jax.numpy as jnp

        assert items, "empty SelectedRows concat"
        h = items[0].height
        ids = jnp.concatenate([s.ids.reshape(-1) for s in items])
        rows = jnp.concatenate([s.rows for s in items], axis=0)
        return SelectedRows(ids, rows, h)


def _sr_flatten(sr):
    return (sr.ids, sr.rows), sr.height


def _sr_unflatten(height, children):
    ids, rows = children
    return SelectedRows(ids, rows, height)


def _register_pytree():
    import jax

    jax.tree_util.register_pytree_node(
        SelectedRows, _sr_flatten, _sr_unflatten
    )


_register_pytree()
