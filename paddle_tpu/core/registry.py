"""Op registry: each op type carries a JAX lowering, optional shape inference,
and a grad-op maker.

Capability parity with the reference's OpRegistry / OpInfoMap / GradOpDescMaker
(reference: paddle/fluid/framework/op_registry.h:197-240, op_info.h,
grad_op_desc_maker.h:34-159), redesigned TPU-first:

  * Instead of per-place kernel maps (OpKernelType{place,dtype,layout,library},
    op_kernel_type.h:27), an op has ONE lowering: a pure JAX function.  XLA owns
    device placement, layout, dtype promotion and fusion — the whole kernel-
    dispatch/data-transform layer (operator.cc:878-971) is deleted by design.
  * The default grad maker does not require hand-written grad kernels: it emits
    a `<type>_grad` op whose lowering calls `jax.vjp` of the forward lowering.
    Hand-written grad makers remain possible for ops with structured sparse
    gradients (e.g. lookup_table -> SelectedRows-style row updates).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

from . import framework as fw

# ---------------------------------------------------------------------------


class LowerContext:
    """Handed to op lowerings at trace time.

    inputs:  slot -> list of jax values (or None for missing optional slots)
    attrs:   op attrs dict
    op:      the IR Operator being lowered
    executor_ctx: trace-scoped state (rng key counter, is_test, mesh, ...)
    """

    def __init__(self, op, attrs, executor_ctx):
        self.op = op
        self.attrs = attrs
        self.executor_ctx = executor_ctx

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def next_rng_key(self):
        return self.executor_ctx.next_rng_key(self.op)

    @property
    def is_test(self):
        return self.executor_ctx.is_test


class OpDef:
    def __init__(
        self,
        type: str,
        lower: Callable,
        infer_shape: Optional[Callable] = None,
        grad_maker: Optional[Callable] = None,
        no_grad: bool = False,
        inplace_outputs: Optional[Dict[str, str]] = None,
        derives_rng=False,
        doc: str = "",
    ):
        self.type = type
        self.lower = lower
        self.infer_shape = infer_shape
        self.grad_maker = grad_maker
        self.no_grad = no_grad
        # output slot -> input slot aliases (optimizer in-place updates)
        self.inplace_outputs = inplace_outputs or {}
        # RNG contract metadata: whether the LOWERING may call
        # ctx.next_rng_key() (draw from the step key).  Either a bool or a
        # predicate `fn(op) -> bool` for ops whose randomness is attr-gated
        # (fused attention weights-dropout).  The executor's step-key
        # threading (executor.op_threads_rng) must cover every op for which
        # this is true — the static verifier (paddle_tpu/analysis) checks
        # that, turning the PR-4 "random op missing from _RANDOM_OPS" bug
        # class into a pre-compile error.
        self.derives_rng = derives_rng
        self.doc = doc

    def op_derives_rng(self, op) -> bool:
        """Whether THIS op instance may draw PRNG bits when lowered."""
        if callable(self.derives_rng):
            return bool(self.derives_rng(op))
        return bool(self.derives_rng)


_registry: Dict[str, OpDef] = {}


def register(
    type: str,
    infer_shape=None,
    grad_maker=None,
    no_grad=False,
    inplace_outputs=None,
    derives_rng=False,
    doc="",
):
    """Decorator registering `fn` as the lowering for op `type`.

    The lowering signature is `fn(ctx, ins) -> {out_slot: [values]}` where
    `ins` maps input slot -> list of traced jax values.  Lowerings that
    call ctx.next_rng_key() MUST declare derives_rng (bool or
    `fn(op) -> bool`); the static verifier cross-checks the declaration
    against the executor's step-key threading.
    """

    def deco(fn):
        if type in _registry:
            raise ValueError(f"op {type!r} already registered")
        _registry[type] = OpDef(
            type,
            fn,
            infer_shape=infer_shape,
            grad_maker=grad_maker,
            no_grad=no_grad,
            inplace_outputs=inplace_outputs,
            derives_rng=derives_rng,
            doc=doc or (fn.__doc__ or ""),
        )
        return fn

    return deco


def lookup(type: str) -> Optional[OpDef]:
    return _registry.get(type)


def get(type: str) -> OpDef:
    opdef = _registry.get(type)
    if opdef is None:
        raise KeyError(
            f"Operator {type!r} has no registered lowering. "
            f"Registered: {sorted(_registry)[:40]}..."
        )
    return opdef


def all_ops() -> List[str]:
    return sorted(_registry)


# ---------------------------------------------------------------------------
# Generic grad machinery
# ---------------------------------------------------------------------------
#
# For forward op X with inputs I, outputs O, the default grad maker emits:
#     X_grad(inputs = I  +  O@GRAD slots) -> I@GRAD slots
# Its lowering re-traces X's forward lowering under jax.vjp and pulls back the
# incoming output cotangents.  This mirrors DefaultGradOpDescMaker
# (grad_op_desc_maker.h:159) but needs no per-op grad code, and because the
# whole program is compiled as one XLA computation, the re-traced forward is
# fused/DCE'd by XLA (no double compute for most ops).


GRAD_SUFFIX = "@GRAD"


def _grad_slot(slot: str) -> str:
    return slot + GRAD_SUFFIX


def default_grad_maker(op, no_grad_set, grad_sub_block_map=None):
    """Build the grad op desc(s) for `op`.  Returns a list of dicts:
    {type, inputs, outputs, attrs} using variable *names*.

    Inputs: all forward input slots (same names) + grad slots for each forward
    output.  Outputs: grad slots for each forward input not in no_grad_set.
    """
    inputs = {slot: list(names) for slot, names in op.inputs.items()}
    for slot, names in op.outputs.items():
        # forward outputs may be needed for the vjp of stateful ops; pass grads
        inputs[_grad_slot(slot)] = [fw.grad_var_name(n) for n in names]
    outputs = {}
    for slot, names in op.inputs.items():
        outs = []
        for n in names:
            if n in no_grad_set:
                outs.append("")  # hole: no grad wanted for this input
            else:
                outs.append(fw.grad_var_name(n))
        outputs[_grad_slot(slot)] = outs
    attrs = dict(op.attrs)
    attrs[fw.OpRole.ROLE_ATTR_NAME] = fw.OpRole.Backward
    return [
        {
            "type": op.type + "_grad",
            "inputs": inputs,
            "outputs": outputs,
            "attrs": attrs,
        }
    ]


def lower_generic_grad(fwd_type: str, ctx: LowerContext, ins):
    """Lowering for `<fwd_type>_grad` ops emitted by default_grad_maker."""
    import jax

    opdef = get(fwd_type)
    fwd_slots = [s for s in ins if not s.endswith(GRAD_SUFFIX)]
    grad_slots = [s for s in ins if s.endswith(GRAD_SUFFIX)]

    fwd_ins = {s: ins[s] for s in fwd_slots}

    # Flatten forward inputs into a list for vjp; remember structure.
    flat_names: List[tuple] = []  # (slot, idx)
    flat_vals: List[Any] = []
    for s in fwd_slots:
        for i, v in enumerate(fwd_ins[s]):
            if v is not None:
                flat_names.append((s, i))
                flat_vals.append(v)

    grad_out_slots = {s: ctx.op.output(s) for s in ctx.op.outputs}

    def fwd_flat(*vals):
        rebuilt = {s: list(fwd_ins[s]) for s in fwd_slots}
        for (s, i), v in zip(flat_names, vals):
            rebuilt[s][i] = v
        sub = LowerContext(ctx.op, ctx.attrs, ctx.executor_ctx)
        outs = opdef.lower(sub, rebuilt)
        # Order output cotangent structure canonically by slot name
        flat_outs = []
        out_index = []
        for slot in sorted(outs):
            for j, ov in enumerate(outs[slot]):
                flat_outs.append(ov)
                out_index.append((slot, j))
        return tuple(flat_outs), out_index

    # Probe to learn output structure (cheap: tracing only)
    _, out_index = fwd_flat(*flat_vals)

    def fwd_only(*vals):
        return fwd_flat(*vals)[0]

    primal_outs, vjp_fn = jax.vjp(fwd_only, *flat_vals)

    # Assemble cotangents for each forward output from incoming grad slots;
    # missing grads (fetch not reached) become zeros.
    import jax.numpy as jnp

    cotangents = []
    for (slot, j), primal in zip(out_index, primal_outs):
        gslot = _grad_slot(slot)
        gvals = ins.get(gslot) or []
        g = gvals[j] if j < len(gvals) else None
        if g is None:
            g = jnp.zeros_like(primal)
        g = jnp.asarray(g, primal.dtype)
        if g.shape != primal.shape:
            g = g.reshape(primal.shape)
        cotangents.append(g)

    in_grads = vjp_fn(tuple(cotangents))

    out: Dict[str, List[Any]] = {}
    grads_by_name = {}
    for (s, i), g in zip(flat_names, in_grads):
        grads_by_name[(s, i)] = g
    for s in fwd_slots:
        gs = []
        for i in range(len(fwd_ins[s])):
            gs.append(grads_by_name.get((s, i)))
        out[_grad_slot(s)] = gs
    return out


def get_grad_lowering(grad_type: str) -> Optional[Callable]:
    """Resolve a lowering for a grad op: registered explicitly, or generic."""
    opdef = lookup(grad_type)
    if opdef is not None:
        return opdef.lower
    if grad_type.endswith("_grad"):
        fwd_type = grad_type[: -len("_grad")]
        if lookup(fwd_type) is not None:
            return functools.partial(lower_generic_grad, fwd_type)
    return None
