"""Remaining book-model integration tests (VERDICT r3 item 9; reference
tests/book/): word2vec (imikolov n-grams), machine_translation (wmt14 +
GRU seq2seq + in-program beam decode), label_semantic_roles (conll05 +
linear-chain CRF)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import framework as fw

rng = np.random.RandomState(17)


def test_word2vec_imikolov():
    """reference tests/book/test_word2vec.py: 4 context embeddings (shared
    table) -> concat -> fc -> softmax over vocab."""
    word_dict = pt.dataset.imikolov.build_dict(synthetic=True)
    n = 5
    data = list(pt.dataset.imikolov.train(word_dict, n, synthetic=True)())
    vocab = len(word_dict)
    emb_dim = 32

    ctx_vars = []
    emb_list = []
    for i in range(n - 1):
        wv = layers.data(name=f"w{i}", shape=[1], dtype="int64")
        ctx_vars.append(wv)
        emb = layers.embedding(wv, size=[vocab, emb_dim],
                               param_attr=pt.ParamAttr(name="shared_emb"))
        emb_list.append(layers.reshape(emb, [-1, emb_dim]))
    target = layers.data(name="target", shape=[1], dtype="int64")
    concat = layers.concat(emb_list, axis=1)
    hidden = layers.fc(concat, size=64, act="sigmoid")
    predict = layers.fc(hidden, size=vocab, act="softmax")
    cost = layers.mean(layers.cross_entropy(input=predict, label=target))
    pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(cost)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    bs = 64
    losses = []
    for epoch in range(16):
        for s in range(0, len(data) - bs, bs):
            chunk = data[s:s + bs]
            feed = {f"w{i}": np.array([[c[i]] for c in chunk], "int64")
                    for i in range(n - 1)}
            feed["target"] = np.array([[c[n - 1]] for c in chunk], "int64")
            (lv,) = exe.run(feed=feed, fetch_list=[cost])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def _pad(seq, length, pad_id=1):
    return (seq + [pad_id] * length)[:length]


def test_machine_translation_wmt14_beam_decode():
    """reference tests/book/test_machine_translation.py over the wmt14
    reader: train the GRU seq2seq, then beam-decode in-program and check
    the learned token transduction."""
    from paddle_tpu.models import seq2seq as S

    dict_size = 40
    seq_len, bs = 12, 32
    data = list(pt.dataset.wmt14.train(dict_size, n_samples=800)())

    train_prog, train_start = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(train_prog, train_start):
            avg_cost = S.build_train_net(
                src_vocab=dict_size, trg_vocab=dict_size,
                src_seq_len=seq_len, trg_seq_len=seq_len)
            pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(avg_cost)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(train_start)

    def batch(i):
        chunk = data[(i * bs) % (len(data) - bs):][:bs]
        return {
            "src_word": np.array(
                [[[t] for t in _pad(c[0], seq_len)] for c in chunk], "int64"),
            "trg_word": np.array(
                [[[t] for t in _pad(c[1], seq_len)] for c in chunk], "int64"),
            "trg_next": np.array(
                [[[t] for t in _pad(c[2], seq_len)] for c in chunk], "int64"),
        }

    losses = []
    for i in range(400):
        (lv,) = exe.run(train_prog, feed=batch(i), fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.35, (losses[0], losses[-1])

    # in-program beam decode through the book path
    dec_b, beam, max_len = 4, 3, seq_len
    dec_prog, dec_start = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(dec_prog, dec_start):
            sent, scores, feeds = S.build_decoder(
                src_vocab=dict_size, trg_vocab=dict_size,
                src_seq_len=seq_len, batch_size=dec_b, beam_size=beam,
                max_out_len=max_len, bos_id=0, eos_id=1)
    fd = batch(0)
    s, sc = exe.run(dec_prog, feed={"src_word": fd["src_word"][:dec_b]},
                    fetch_list=[sent, scores])
    s, sc = np.asarray(s), np.asarray(sc)
    assert s.shape == (dec_b, beam, max_len)
    assert np.all(np.diff(sc, axis=1) <= 1e-5)  # beams sorted best-first
    # compare beam-0 prefixes against the true key-chain target
    hits = total = 0
    for i in range(dec_b):
        src_ids = [t[0] for t in fd["src_word"][i] if t[0] not in (0, 1)]
        expect = pt.dataset.wmt14.synthetic_target(src_ids, dict_size)
        got = [t for t in s[i, 0] if t not in (0, 1)]
        m = min(len(expect), len(got), 6)
        hits += sum(1 for a, b_ in zip(expect[:m], got[:m]) if a == b_)
        total += m
    assert total > 0 and hits / total > 0.5, (hits, total, s[:, 0])


def test_label_semantic_roles_conll05_crf():
    """reference tests/book/test_label_semantic_roles.py: the 9-slot SRL
    features -> shared embeddings -> fc -> linear-chain CRF loss, with
    crf_decoding accuracy improving."""
    samples = list(pt.dataset.conll05.test(synthetic=True, n_samples=200)())
    word_dict = pt.dataset.conll05.word_dict(synthetic=True)
    label_dict = pt.dataset.conll05.label_dict(synthetic=True)
    vocab = len(word_dict)
    n_labels = len(label_dict)
    seq_len, bs, emb = 18, 16, 24

    slots = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
             "verb", "mark"]
    feats = []
    for name in slots:
        v = layers.data(name=name, shape=[seq_len], dtype="int64")
        size = 2 if name == "mark" else vocab
        e = layers.embedding(v, size=[max(size, 64), emb])
        feats.append(e)
    target = layers.data(name="target", shape=[seq_len], dtype="int64")
    length = layers.data(name="length", shape=[], dtype="int64")

    feat = layers.concat(feats, axis=2)                   # [B, T, 8*emb]
    # bidirectional GRU like the reference's stacked bi-LSTM SRL encoder
    proj_f = layers.fc(feat, size=3 * 32, num_flatten_dims=2)
    proj_b = layers.fc(feat, size=3 * 32, num_flatten_dims=2)
    fwd = layers.dynamic_gru(proj_f, size=32, length=length)
    bwd = layers.dynamic_gru(proj_b, size=32, is_reverse=True,
                             length=length)
    hidden = layers.fc(layers.concat([fwd, bwd], axis=2), size=64,
                       num_flatten_dims=2, act="tanh")
    emission = layers.fc(hidden, size=n_labels, num_flatten_dims=2)
    crf_cost = layers.linear_chain_crf(
        emission, target, length=length,
        param_attr=pt.ParamAttr(name="crf_w"))
    avg_cost = layers.mean(crf_cost)
    decode = layers.crf_decoding(emission, length=length,
                                 param_attr=pt.ParamAttr(name="crf_w"))
    pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(avg_cost)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    def batch(i):
        chunk = samples[(i * bs) % (len(samples) - bs):][:bs]
        feed = {}
        for si, name in enumerate(slots):
            feed[name] = np.array(
                [_pad(list(c[si]), seq_len, 0) for c in chunk], "int64")
        feed["target"] = np.array(
            [_pad(list(c[8]), seq_len, 0) for c in chunk], "int64")
        feed["length"] = np.array([len(c[0]) for c in chunk], "int64")
        return feed

    losses = []
    for i in range(100):
        (lv,) = exe.run(feed=batch(i), fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.4, (losses[0], losses[-1])

    fd = batch(0)
    test_prog = pt.default_main_program().clone(for_test=True)
    (path,) = exe.run(test_prog, feed=fd, fetch_list=[decode])
    path = np.asarray(path)
    correct = total = 0
    for i in range(bs):
        ln = int(fd["length"][i])
        correct += (path[i, :ln] == fd["target"][i, :ln]).sum()
        total += ln
    assert correct / total > 0.8, correct / total
