// Chunked, seekable, CRC-checked record file format — the native data-plane
// component of paddle_tpu (reference: paddle/fluid/recordio/ — Header
// header.h:39, Chunk chunk.h:27, Writer writer.h:22, Scanner scanner.h; the
// reference's is C++ too, and chunk-seekability is what enables the
// master's task-splitting / sharded readers).
//
// File = sequence of chunks:
//   u32 magic | u32 num_records | u32 payload_len | u32 payload_crc32
//   payload = num_records * u32 record lengths, then record bytes.
// All little-endian.  Exposed as a C ABI for ctypes (no pybind11 in the
// image); paddle_tpu/recordio.py holds the Python face + a pure-Python
// fallback writer/scanner for environments without a toolchain.
//
// Build: g++ -O2 -shared -fPIC recordio.cc -o librecordio.so -lz

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x43525450;  // "PTRC" little-endian

struct Writer {
  FILE* f = nullptr;
  std::vector<uint32_t> lengths;
  std::string payload;
  uint32_t max_chunk_bytes = 1 << 20;

  int flush() {
    if (lengths.empty()) return 0;
    std::string body;
    body.reserve(lengths.size() * 4 + payload.size());
    for (uint32_t len : lengths) {
      body.append(reinterpret_cast<const char*>(&len), 4);
    }
    body.append(payload);
    uint32_t header[4] = {
        kMagic, static_cast<uint32_t>(lengths.size()),
        static_cast<uint32_t>(body.size()),
        static_cast<uint32_t>(
            crc32(0, reinterpret_cast<const Bytef*>(body.data()),
                  body.size())),
    };
    if (fwrite(header, 4, 4, f) != 4) return -1;
    if (!body.empty() && fwrite(body.data(), 1, body.size(), f) !=
        body.size()) {
      return -1;
    }
    lengths.clear();
    payload.clear();
    return 0;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<int64_t> chunk_offsets;  // file offset of each chunk header
  // current chunk state
  std::vector<uint32_t> lengths;
  std::string payload;          // record bytes only
  size_t record_idx = 0;
  size_t byte_off = 0;
  size_t next_chunk = 0;        // index into chunk_offsets

  int index() {
    chunk_offsets.clear();
    if (fseek(f, 0, SEEK_END) != 0) return -1;
    int64_t file_size = ftell(f);
    int64_t off = 0;
    while (off + 16 <= file_size) {
      uint32_t header[4];
      if (fseek(f, off, SEEK_SET) != 0) return -1;
      if (fread(header, 4, 4, f) != 4) return -1;
      if (header[0] != kMagic) return -2;  // corrupt
      chunk_offsets.push_back(off);
      off += 16 + static_cast<int64_t>(header[2]);
    }
    return off == file_size ? 0 : -2;
  }

  // load chunk i into memory; -2 = corrupt/crc, -1 = io error
  int load_chunk(size_t i) {
    if (i >= chunk_offsets.size()) return 1;  // EOF
    uint32_t header[4];
    if (fseek(f, chunk_offsets[i], SEEK_SET) != 0) return -1;
    if (fread(header, 4, 4, f) != 4) return -1;
    uint32_t num = header[1], payload_len = header[2], want_crc = header[3];
    std::string body(payload_len, '\0');
    if (payload_len &&
        fread(&body[0], 1, payload_len, f) != payload_len) {
      return -1;
    }
    uint32_t got_crc = crc32(
        0, reinterpret_cast<const Bytef*>(body.data()), body.size());
    if (got_crc != want_crc) return -2;
    if (static_cast<size_t>(num) * 4 > body.size()) return -2;
    lengths.assign(
        reinterpret_cast<const uint32_t*>(body.data()),
        reinterpret_cast<const uint32_t*>(body.data()) + num);
    payload = body.substr(num * 4);
    record_idx = 0;
    byte_off = 0;
    return 0;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, uint32_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  if (max_chunk_bytes) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int rio_write(void* handle, const char* buf, uint32_t len) {
  auto* w = static_cast<Writer*>(handle);
  w->lengths.push_back(len);
  w->payload.append(buf, len);
  if (w->payload.size() >= w->max_chunk_bytes) return w->flush();
  return 0;
}

int rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = w->flush();
  if (fclose(w->f) != 0) rc = -1;
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  if (s->index() != 0) {
    fclose(f);
    delete s;
    return nullptr;
  }
  return s;
}

int64_t rio_num_chunks(void* handle) {
  return static_cast<Scanner*>(handle)->chunk_offsets.size();
}

// position the scanner at the start of chunk i (for sharded reads)
int rio_seek_chunk(void* handle, int64_t i) {
  auto* s = static_cast<Scanner*>(handle);
  s->next_chunk = static_cast<size_t>(i);
  s->lengths.clear();
  s->payload.clear();
  s->record_idx = 0;
  s->byte_off = 0;
  return 0;
}

// next record in the CURRENT chunk only; 1 = chunk exhausted
int64_t rio_next_in_chunk(void* handle, const char** out) {
  auto* s = static_cast<Scanner*>(handle);
  if (s->record_idx >= s->lengths.size()) return -3;  // chunk exhausted
  uint32_t len = s->lengths[s->record_idx++];
  *out = s->payload.data() + s->byte_off;
  s->byte_off += len;
  return len;
}

// load the chunk at next_chunk and advance; 1 = EOF, <0 = error
int rio_load_next_chunk(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  int rc = s->load_chunk(s->next_chunk);
  if (rc == 0) s->next_chunk++;
  return rc;
}

void rio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
